"""Actor API — analog of the reference's python/ray/actor.py (ActorClass
._remote :275,:851; ActorHandle; ActorMethod). Creation is conductor-mediated
(reference gcs_actor_manager.cc); steady-state method calls go directly to the
actor's worker with per-caller sequence numbers for ordering."""
from __future__ import annotations

import os
import threading
import uuid
from typing import Any, Dict, Optional

from . import exceptions as exc
from ._private import worker as worker_mod


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    num_returns=self._num_returns())

    def options(self, num_returns: int = 1):
        m = ActorMethod(self._handle, self._name)
        m._override_num_returns = num_returns
        return m

    def bind(self, *args, **kwargs):
        """Build a lazy DAG node — reference python/ray/dag/class_node.py
        ClassMethodNode via actor.py bind()."""
        from .dag import ClassMethodNode
        return ClassMethodNode(self._handle, self._name, args, kwargs)

    def _num_returns(self) -> int:
        return getattr(self, "_override_num_returns", 1)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name} cannot be called directly; use "
            f".{self._name}.remote(...)")


class ActorHandle:
    """Client-side handle. Each handle keeps its own monotonically increasing
    sequence number so the server can execute this caller's requests in
    submission order (reference sequential_actor_submit_queue.cc)."""

    def __init__(self, actor_id: str, address, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._address = tuple(address) if address else None
        self._max_task_retries = max_task_retries
        self._caller_id = uuid.uuid4().hex
        self._seqno = 0
        self._lock = threading.Lock()

    @property
    def actor_id(self) -> str:
        return self._actor_id

    def _invoke(self, method: str, args, kwargs, num_returns: int = 1):
        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError("ray_tpu.init() must be called first")
        with self._lock:
            seqno = self._seqno
            self._seqno += 1

        def submit():
            return w.submit_actor_task(
                self._actor_id, self._address, method, args, kwargs,
                num_returns, seqno, self._caller_id,
                max_task_retries=self._max_task_retries)

        # Unified timeline: submission span parents the actor-side
        # execution span (see remote_function.remote for the rationale).
        # Qualified with the actor id like task events name actor calls
        # ("<id8>.<method>") so same-named methods of different actors
        # stay distinguishable in the merged trace.
        if os.environ.get("RAY_TPU_TRACING") == "1":
            from .util import tracing

            with tracing.submit_span(f"{self._actor_id[:8]}.{method}"):
                return submit()
        return submit()

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __reduce__(self):
        return (ActorHandle,
                (self._actor_id, self._address, self._max_task_retries))

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:12]}…)"


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated "
            f"directly; use {self._cls.__name__}.remote(...)")

    def options(self, **overrides) -> "ActorClass":
        opts = dict(self._options)
        opts.update(overrides)
        return ActorClass(self._cls, opts)

    def remote(self, *args, **kwargs) -> ActorHandle:
        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError("ray_tpu.init() must be called first")
        o = dict(self._options)
        pg = o.get("placement_group")
        if pg is not None:
            o["placement_group_id"] = getattr(pg, "id", pg)
        if o.get("num_tpus") is not None:
            o.setdefault("resources", {})
            o["resources"] = dict(o["resources"] or {})
            o["resources"]["TPU"] = float(o.pop("num_tpus"))
        info = w.create_actor(self._cls, args, kwargs, o)
        return ActorHandle(info["actor_id"], info["address"],
                           max_task_retries=o.get("max_task_retries", 0))

    @property
    def underlying_class(self):
        return self._cls


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called first")
    info = w.conductor.call("get_actor_info", None, name, namespace, 30.0,
                            timeout=60.0)
    if info["state"] == "DEAD":
        raise exc.ActorDiedError(info["actor_id"],
                                 info.get("death_cause") or "")
    if info["address"] is None:
        raise exc.ActorUnavailableError(
            info["actor_id"], f"actor {name!r} not placed within timeout "
            f"(state={info['state']})")
    return ActorHandle(info["actor_id"], info["address"],
                       max_task_retries=info.get("max_task_retries", 0))


def exit_actor() -> None:
    """Terminate the current actor gracefully after the in-flight call
    completes (reference: ray.actor.exit_actor / __ray_terminate__)."""
    raise SystemExit(0)
