"""Sampler actors: the Sebulba half of the Podracer split.

Each sampler is one actor process wrapping a
``ContinuousBatchingEngine`` behind a ``WeightSync``: the engine
decodes rollouts continuously while the sync thread prefetches each
newly published version's chunks and hot-swaps BETWEEN decode ticks —
the framework keeps the sampler fresh; generation never restarts,
in-flight requests keep their KV caches and continue under the new
weights from the next tick on.

A rollout is a small host-side dict::

    {"prompt": int32[...], "completion": int32[...],
     "scores": float32[...],             # per-token logprobs
     "weights_version": int,             # serving when it COMPLETED
     "weights_version_start": int,       # serving when it was submitted
     "sampler": str, "ts": float}

A swap landing mid-rollout means mixed provenance: start != end tags
it (a PPO-style consumer should drop or re-weight those; plain
distillation does not care).

Completed rollouts are pushed to the :class:`RolloutBuffer`; a full
buffer REJECTS the overflow and the sampler pauses generation (holding
the rejected rollouts for retry) — backpressure propagates to the
engine instead of growing an unbounded queue.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .metrics import online_metrics


def default_prompt_fn(vocab_size: int, min_len: int = 2,
                      max_len: int = 8) -> Callable:
    """Random-token prompt generator bounded to the model's vocab (the
    one default both RolloutSampler and OnlineTrainer use)."""

    def prompt_fn(rng: np.random.Generator) -> List[int]:
        n = int(rng.integers(min_len, max_len + 1))
        return rng.integers(1, max(2, int(vocab_size)),
                            size=n).tolist()

    return prompt_fn


class RolloutSampler:
    """Actor body for one sampler (spawn via :func:`spawn_samplers` or
    ``ray_tpu.remote(RolloutSampler).remote(...)``).

    `model_factory()` runs inside the actor and returns
    ``(template_params, config)`` — the template's shardings/dtypes are
    the sampler's serving layout (reshard-on-fetch), `config` is any
    family the engine knows (GPT2Config, LlamaConfig)."""

    def __init__(self, sampler_id: str, weights_name: str,
                 model_factory: Callable[[], Any], buffer: Any, *,
                 max_new_tokens: int = 16,
                 eos_token: Optional[int] = None,
                 min_version: int = 1,
                 wait_timeout_s: float = 120.0,
                 max_batch: int = 2,
                 prompt_fn: Optional[Callable] = None,
                 seed: int = 0,
                 poll_interval_s: float = 0.05,
                 prefetch: bool = True):
        from ray_tpu import weights as wts
        from ray_tpu.models.engine import ContinuousBatchingEngine

        self.sampler_id = sampler_id
        self.weights_name = weights_name
        self.buffer = buffer
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token = eos_token
        self._rng = np.random.default_rng(seed)
        template, self.config = model_factory()
        self.prompt_fn = prompt_fn or default_prompt_fn(
            getattr(self.config, "vocab_size", 256))
        # the learner publishes the first version before samplers spawn;
        # wait for it rather than serving uninitialized weights
        self._sub = wts.WeightSubscriber(weights_name)
        version = self._sub.wait_for_version(min_version,
                                             timeout=wait_timeout_s)
        params = self._sub.fetch(version=version, like=template)
        self.engine = ContinuousBatchingEngine(
            params, self.config, max_batch=max_batch,
            params_version=version)
        self.sync = wts.WeightSync(
            self.engine, weights_name, template=params,
            consumer=sampler_id, poll_interval_s=poll_interval_s,
            subscriber=self._sub, prefetch=prefetch)
        self.rollouts = 0
        self.rollout_tokens = 0
        self.backpressure_waits = 0
        self._seen_version = version
        # staleness high-water mark, probed at every rollout boundary —
        # the loop's freshness invariant (<= 1) is asserted from this
        self.max_staleness: Optional[int] = None
        self._held: List[Dict[str, Any]] = []  # rejected, awaiting retry
        self.run_error: Optional[str] = None  # why the loop died, if it did
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_push = 0.0

    # ---------------------------------------------------------- lifecycle

    def start(self) -> bool:
        """Begin the rollout loop on a background thread (the actor's
        RPC loop stays free for status()/stop())."""
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"sampler-{self.sampler_id}")
            self._thread.start()
        return True

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
        self.sync.stop()  # closes the shared subscriber too
        self.engine.stop()
        st = self.status()
        self._push_telemetry(force=True)
        return st

    # --------------------------------------------------------------- loop

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self._held:
                    # backpressure: the buffer rejected these — retry
                    # before generating anything new. Telemetry still
                    # pushes: the learner's publication gate reads
                    # serving_version from these snapshots, and a
                    # frozen one would defer publishes on stale data
                    if not self._flush():
                        self.backpressure_waits += 1
                        self._push_telemetry()
                        self._stop.wait(0.02)
                        continue
                self._held.append(self._rollout_one())
                self._flush()
                self._push_telemetry()
            except Exception as e:  # noqa: BLE001 — a dead rollout
                # thread must be VISIBLE: record the cause, push a
                # final snapshot, and stop (a healthy-looking actor
                # with a silently-dead loop would hang the learner in
                # data_wait forever)
                self.run_error = f"{type(e).__name__}: {e}"
                self._push_telemetry(force=True)
                return

    def _rollout_one(self) -> Dict[str, Any]:
        prompt = list(self.prompt_fn(self._rng))
        version_start = self.engine.params_version
        stream = self.engine.stream(prompt, self.max_new_tokens,
                                    self.eos_token)
        completion = list(stream)
        scores = stream.scores
        version = self.engine.params_version
        self.rollouts += 1
        self.rollout_tokens += len(completion)
        m = online_metrics()
        m["rollouts"].inc(1, tags={"sampler": self.sampler_id})
        m["rollout_tokens"].inc(len(completion),
                                tags={"sampler": self.sampler_id})
        self._event({"kind": "rollout", "sampler": self.sampler_id,
                     "tokens": len(completion),
                     "weights_version": version})
        if version is not None and version != self._seen_version:
            # the sync thread swapped while we decoded: mark it in the
            # online lane (the weights lane has the fabric-side marker)
            self._event({"kind": "swap", "sampler": self.sampler_id,
                         "from_version": self._seen_version,
                         "to_version": version})
            self._seen_version = version
        return {"prompt": np.asarray(prompt, np.int32),
                "completion": np.asarray(completion, np.int32),
                "scores": np.asarray(scores, np.float32),
                "weights_version": version,
                "weights_version_start": version_start,
                "sampler": self.sampler_id, "ts": time.time()}

    def _flush(self) -> bool:
        """Push held rollouts to the buffer; True when all landed."""
        import ray_tpu

        if not self._held:
            return True
        accepted = ray_tpu.get(
            self.buffer.put.remote(list(self._held)), timeout=60.0)
        del self._held[:accepted]
        return not self._held

    # ---------------------------------------------------------- telemetry

    def status(self) -> Dict[str, Any]:
        sync = self.sync.status()
        # the sync loop samples staleness every poll cycle; fold its
        # high-water mark into ours
        for st in (sync["staleness_versions"],
                   sync["max_staleness_versions"]):
            if st is not None:
                self.max_staleness = st if self.max_staleness is None \
                    else max(self.max_staleness, st)
        return {
            "role": "sampler", "sampler": self.sampler_id,
            "weights_name": self.weights_name,
            "rollouts": self.rollouts,
            "rollout_tokens": self.rollout_tokens,
            "held": len(self._held),
            "backpressure_waits": self.backpressure_waits,
            "run_error": self.run_error,
            "max_staleness_versions": self.max_staleness,
            "serving_version": sync["serving_version"],
            "latest_version": sync["latest_version"],
            "staleness_versions": sync["staleness_versions"],
            "registry_reachable": sync["registry_reachable"],
            "swap_count": sync["swap_count"],
            "prefetch_bytes": sync["prefetch_bytes"],
            "rpc_bytes": sync["rpc_bytes"],
            "shm_bytes": sync["shm_bytes"],
            "fetched_bytes": sync["fetched_bytes"],
        }

    def _push_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.25:
            return
        self._last_push = now
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return
        try:
            w.conductor.notify("report_online_stats", w.worker_id,
                               f"sampler/{self.sampler_id}",
                               self.status())
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass

    def _event(self, event: Dict[str, Any]) -> None:
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return
        try:
            w.conductor.notify("report_online_event", event)
        except Exception:  # noqa: BLE001 — telemetry only
            pass


def spawn_samplers(num_samplers: int, weights_name: str,
                   model_factory: Callable[[], Any], buffer: Any, *,
                   name_prefix: str = "sampler",
                   **sampler_kwargs) -> List[Any]:
    """Spawn N sampler actors (one process each) against one weight set
    and one buffer; returns the actor handles. Each gets a distinct
    sampler id and rng seed."""
    import ray_tpu

    base_seed = int(sampler_kwargs.pop("seed", 0))
    actor_cls = ray_tpu.remote(RolloutSampler)
    return [actor_cls.remote(
        f"{name_prefix}-{i}", weights_name, model_factory, buffer,
        seed=base_seed + i, **sampler_kwargs)
        for i in range(num_samplers)]
