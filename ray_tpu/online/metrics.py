"""Prometheus surface of the online learning loop — lazily created so
importing ray_tpu.online never spawns a metrics pusher (the weights /
kvcache / mpmd pattern). All ride the util.metrics conductor-push
pipeline into /api/metrics and `ray_tpu metrics`:

- ray_tpu_online_rollout_tokens_total{sampler}   tokens generated into
                                                 rollouts, per sampler
- ray_tpu_online_rollouts_total{sampler}         completed rollouts
- ray_tpu_online_buffer_occupancy{buffer}        rollouts queued in the
                                                 buffer right now
- ray_tpu_online_buffer_rejected_total{buffer}   backpressured puts
- ray_tpu_online_ingested_rollouts_total{run}    rollouts the learner
                                                 consumed (ingest rate)

Sampler staleness deliberately has no twin here: it IS the existing
``ray_tpu_weights_staleness_versions`` gauge (each sampler's WeightSync
sets it under consumer=<sampler id>) — one number, one gauge.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

# Rebound ONCE, to a fully-built dict: the unlocked fast path can only
# ever observe None or the complete registry, never a partial one.
_metrics: Optional[Dict[str, Any]] = None
_lock = threading.Lock()


def online_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                rollout_tokens=Counter(
                    "ray_tpu_online_rollout_tokens_total",
                    "tokens generated into rollouts by online-loop "
                    "samplers", tag_keys=("sampler",)),
                rollouts=Counter(
                    "ray_tpu_online_rollouts_total",
                    "rollouts completed by online-loop samplers",
                    tag_keys=("sampler",)),
                buffer_occupancy=Gauge(
                    "ray_tpu_online_buffer_occupancy",
                    "rollouts currently queued in the online-loop "
                    "buffer", tag_keys=("buffer",)),
                buffer_rejected=Counter(
                    "ray_tpu_online_buffer_rejected_total",
                    "rollout puts rejected by a full buffer "
                    "(sampler backpressure)", tag_keys=("buffer",)),
                ingested_rollouts=Counter(
                    "ray_tpu_online_ingested_rollouts_total",
                    "rollouts the online learner pulled into training "
                    "batches", tag_keys=("run",)))
    return _metrics
