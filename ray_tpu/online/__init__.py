"""ray_tpu.online — the Podracer-style online learning loop.

Closes the train→serve→train cycle the runtime's three legs enable
(training gangs, the continuous-batching inference engine, the live
weight fabric) with the Anakin/Sebulba split from the Podracer
architectures paper (arXiv 2104.06272):

- **Samplers** (:class:`RolloutSampler` / :func:`spawn_samplers`):
  decoupled actor processes, each wrapping a
  ``ContinuousBatchingEngine`` behind a ``WeightSync`` so weights
  hot-swap BETWEEN decode ticks with no restart — the framework, not
  the user, keeps samplers fresh. Rollouts carry the prompt, the
  sampled completion, per-token logprob scores, and the weights
  version that produced them.
- **Rollout buffer** (:class:`RolloutBuffer` / :func:`from_rollouts`):
  a bounded actor between samplers and the learner. ``put`` applies
  backpressure (a full buffer rejects, samplers pause instead of
  flooding the object plane); ``from_rollouts()`` exposes it through
  the Data streaming-split contract (``streaming_split`` per learner
  host, background prefetch) so ingestion overlaps the device step and
  ``data_wait`` stays a flight-recorder phase.
- **Learner** (:class:`OnlineTrainer`): a JaxTrainer gang reusing
  ``TrainStep``/gang formation, training an online-distillation
  objective on the rollout stream and publishing weights every K steps
  via ``train.report(publish_weights=..., weights_delta=True)`` — the
  weight fabric's delta publication ships only the leaves the
  optimizer actually moved.

The freshness invariant the loop maintains: sampler staleness (the
``ray_tpu_weights_staleness_versions`` gauge) stays <= 1 version while
the learner steps at full speed — ingestion and weight refresh both
live off the critical path.

Surfaces: ``util.state.online_status()``, ``ray_tpu online`` CLI,
dashboard ``/api/online``, lazy Prometheus metrics
(``ray_tpu_online_*``), and an ``online`` lane of
rollout/publish/swap/ingest markers in the merged timeline.
"""
from .buffer import RolloutBuffer, RolloutStream, from_rollouts  # noqa: F401
from .loop import OnlineConfig, OnlineResult, OnlineTrainer  # noqa: F401
from .lora import TenantLoraTrainer  # noqa: F401
from .sampler import RolloutSampler, spawn_samplers  # noqa: F401

__all__ = ["OnlineConfig", "OnlineResult", "OnlineTrainer",
           "RolloutBuffer", "RolloutSampler", "RolloutStream",
           "TenantLoraTrainer", "from_rollouts", "spawn_samplers"]
