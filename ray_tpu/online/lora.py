"""Per-tenant online LoRA loop: adapter-only training against a frozen
base, published as weight-fabric deltas that hot-swap into serving.

The multi-tenant closing of the Podracer cycle (serve/lora.py is the
serving half): one :class:`TenantLoraTrainer` per tenant optimizes ONLY
its adapter's A/B leaves — the base params are a frozen closure
constant, never touched, never republished — and publishes the adapter
tree under ``lora/<tenant>`` every ``publish_every`` steps
(delta publication: an adapter refresh ships only the leaves the
optimizer moved). Every serving replica's
:class:`~ray_tpu.serve.lora.FabricAdapterSource` sees the pubsub
notice, marks the tenant dirty, and hot-swaps the new version between
decode ticks — without restarting anything and without perturbing any
OTHER tenant's in-flight requests (asserted in tests/test_lora.py).

Versions continue after whatever the registry already holds
(:func:`ray_tpu.online.loop.next_publish_version` — the same rule the
full OnlineTrainer follows), so a restarted tenant loop never collides
with its own history. The PPO-style objective over rollout logprob
scores stays the recorded follow-up (ROADMAP); today's objective is
next-token CE on whatever batches the caller feeds (distillation from
a tenant corpus, or the tenant's own rollouts).
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Iterable, List, Optional

import numpy as np


def _full_forward(config) -> Callable:
    from ray_tpu.models.llama import LlamaConfig, llama_forward

    if isinstance(config, LlamaConfig):
        return llama_forward
    from ray_tpu.models.gpt2 import GPT2Config, gpt2_forward

    if isinstance(config, GPT2Config):
        return gpt2_forward
    raise TypeError(f"no LoRA training support for "
                    f"{type(config).__name__}")


class TenantLoraTrainer:
    """Adapter-only trainer for one tenant.

    ``step(tokens)`` takes one ``[B, T] int32`` batch, runs a
    next-token CE step whose gradients flow ONLY into the adapter's
    A/B leaves (the base enters the jitted loss as a plain argument
    and never receives an update), and returns the loss. ``publish()``
    ships the current adapter to the weight fabric; ``fit()`` is the
    step/publish cadence loop."""

    def __init__(self, base_params: Any, model_config: Any, tenant: str,
                 *, rank: int = 4, scale: float = 1.0,
                 learning_rate: float = 1e-2, publish_every: int = 2,
                 prefix: str = "lora/", seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.generate import (lora_targets,
                                             merge_lora_params)

        self.base_params = base_params
        self.model_config = model_config
        self.tenant = str(tenant)
        self.rank = int(rank)
        self.scale = float(scale)
        self.publish_every = max(1, int(publish_every))
        self.prefix = prefix
        self.published_versions: List[int] = []
        self.losses: List[float] = []
        self._step = 0
        layers = int(model_config.num_layers)
        rng = np.random.default_rng(seed)
        # classic LoRA init: A random, B zero — the adapter starts as
        # an exact no-op and grows away from the base as it trains
        self._ab: Dict[str, Dict[str, Any]] = {}
        for name, d_in, d_out in lora_targets(model_config):
            self._ab[name] = {
                "a": jnp.asarray(
                    rng.standard_normal((layers, d_in, self.rank))
                    * 0.02, jnp.float32),
                "b": jnp.zeros((layers, self.rank, d_out), jnp.float32),
            }
        self._opt = optax.adam(learning_rate)
        self._opt_state = self._opt.init(self._ab)
        fwd = _full_forward(model_config)
        cfg = model_config
        sc = jnp.float32(self.scale)

        def loss_fn(ab, base, tokens):
            merged = merge_lora_params(
                base, cfg, {"scale": sc, "targets": ab})
            logits = fwd(merged, tokens[:, :-1], cfg)
            logits = logits[..., :cfg.vocab_size]
            logp = jax.nn.log_softmax(logits, axis=-1)
            tgt = tokens[:, 1:]
            ll = jnp.take_along_axis(logp, tgt[..., None],
                                     axis=-1)[..., 0]
            return -jnp.mean(ll)

        grad_fn = jax.value_and_grad(loss_fn)

        @jax.jit
        def train_step(ab, opt_state, base, tokens):
            loss, grads = grad_fn(ab, base, tokens)
            updates, opt_state = self._opt.update(grads, opt_state, ab)
            return optax.apply_updates(ab, updates), opt_state, loss

        self._train_step = train_step

    # ------------------------------------------------------------- steps

    def step(self, tokens) -> float:
        tokens = np.asarray(tokens, np.int32)
        self._ab, self._opt_state, loss = self._train_step(
            self._ab, self._opt_state, self.base_params, tokens)
        self._step += 1
        loss = float(loss)
        self.losses.append(loss)
        return loss

    def adapter(self) -> Dict[str, Any]:
        """The current adapter as the host tree the serving pool pages
        (serve/lora.py layout)."""
        return {
            "scale": np.float32(self.scale),
            "targets": {name: {"a": np.asarray(ab["a"]),
                               "b": np.asarray(ab["b"])}
                        for name, ab in self._ab.items()},
        }

    def publish(self, *, delta: bool = True) -> int:
        """Publish the current adapter under ``lora/<tenant>``; the
        committed version is appended to ``published_versions``."""
        from ray_tpu.online.loop import next_publish_version
        from ray_tpu.serve.lora import tenant_weights_name
        from ray_tpu.weights import publish

        name = tenant_weights_name(self.tenant, self.prefix)
        version = next_publish_version(name)
        publish(self.adapter(), name=name, version=version,
                delta=delta)
        self.published_versions.append(version)
        return version

    def fit(self, batches: Iterable[Any],
            num_steps: Optional[int] = None,
            delta: bool = True) -> Dict[str, Any]:
        """Run the step/publish cadence over `batches` (each a
        ``[B, T]`` token array). Publishes every ``publish_every``
        steps and once more at the end if steps remain unpublished."""
        steps_since_publish = 0
        for i, batch in enumerate(batches):
            if num_steps is not None and i >= num_steps:
                break
            self.step(batch)
            steps_since_publish += 1
            if steps_since_publish >= self.publish_every:
                self.publish(delta=delta)
                steps_since_publish = 0
        if steps_since_publish:
            self.publish(delta=delta)
        return {"tenant": self.tenant, "steps": self._step,
                "losses": list(self.losses),
                "published_versions": list(self.published_versions)}


__all__ = ["TenantLoraTrainer"]
