"""Rollout buffer: the bounded, version-tagged queue between sampler
actors and the learner gang.

The buffer is a single actor holding rollout DICTS (prompt/completion
token arrays, per-token scores, the weights version that produced them
— small host arrays, never device buffers). Flow control is explicit:

- ``put`` accepts up to the free capacity and REJECTS the rest
  (returning the accepted count) — a full buffer pushes back on the
  samplers, which pause generation instead of flooding the object
  plane. Rollouts that an engine already produced are never dropped
  from the buffer side; the sampler retries the same batch.
- ``get_batch`` pops FIFO, so two learner hosts pulling through
  ``streaming_split`` consume disjoint rollouts by construction.

:func:`from_rollouts` exposes the buffer through the Data
streaming-split contract the Train-equivalent expects
(``streaming_split(world)[rank]`` → per-host iterator): each shard's
``iter_batches`` runs a background prefetch thread that pulls (and
collates) the NEXT batch while the learner's device step runs on the
current one — ingestion overlaps compute, and the residual wait the
learner actually observes lands in the flight recorder's ``data_wait``
phase. On shutdown, rollouts accumulated but not yet collated are
handed back to the buffer; an already-collated batch parked in the
prefetch queue (at most ``prefetch`` batches) is the one thing a
stopping learner discards.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .metrics import online_metrics


class RolloutBuffer:
    """Actor body for the rollout queue (spawn via
    ``ray_tpu.remote(RolloutBuffer).options(name=...).remote(...)``)."""

    def __init__(self, capacity: int = 256, name: str = "rollouts"):
        if capacity <= 0:
            raise ValueError(f"capacity must be > 0, got {capacity}")
        self.capacity = int(capacity)
        self.name = name
        self._items: "collections.deque[Dict[str, Any]]" = \
            collections.deque()
        self._lock = threading.Lock()
        self.puts = 0
        self.rejected = 0
        self.gets = 0
        self.total_in = 0
        self.total_out = 0
        self._versions: Dict[int, int] = {}  # weights_version -> queued
        self._last_push = 0.0

    # ------------------------------------------------------------- queue

    def put(self, rollouts: List[Dict[str, Any]]) -> int:
        """Enqueue up to the free capacity; returns how many were
        accepted (the backpressure signal — 0 means "full, hold on")."""
        with self._lock:
            free = self.capacity - len(self._items)
            accepted = rollouts[:max(0, free)]
            for r in accepted:
                self._items.append(r)
                v = r.get("weights_version")
                if v is not None:
                    self._versions[int(v)] = \
                        self._versions.get(int(v), 0) + 1
            self.puts += 1
            self.total_in += len(accepted)
            n_rej = len(rollouts) - len(accepted)
            self.rejected += n_rej
        if n_rej:
            online_metrics()["buffer_rejected"].inc(
                n_rej, tags={"buffer": self.name})
        self._publish_telemetry()
        return len(accepted)

    def get_batch(self, max_items: int) -> List[Dict[str, Any]]:
        """Pop up to `max_items` FIFO (non-blocking: the consumer owns
        its wait policy)."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            while self._items and len(out) < max_items:
                r = self._items.popleft()
                v = r.get("weights_version")
                if v is not None:
                    left = self._versions.get(int(v), 0) - 1
                    if left > 0:
                        self._versions[int(v)] = left
                    else:
                        self._versions.pop(int(v), None)
                out.append(r)
            self.gets += 1
            self.total_out += len(out)
        self._publish_telemetry()
        return out

    def size(self) -> int:
        with self._lock:
            return len(self._items)

    # --------------------------------------------------------- telemetry

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "role": "buffer", "buffer": self.name,
                "capacity": self.capacity,
                "occupancy": len(self._items),
                "puts": self.puts, "gets": self.gets,
                "rejected": self.rejected,
                "total_in": self.total_in, "total_out": self.total_out,
                "versions_queued": dict(self._versions),
            }

    def _publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.25:
            return
        self._last_push = now
        st = self.stats()
        online_metrics()["buffer_occupancy"].set(
            st["occupancy"], tags={"buffer": self.name})
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return
        try:
            w.conductor.notify("report_online_stats", w.worker_id,
                               f"buffer/{self.name}", st)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass


# --------------------------------------------------- learner-side stream


class RolloutShard:
    """One learner host's iterator over the shared buffer (the
    ``get_dataset_shard`` handle). Destructive FIFO pops make shards
    disjoint without any partitioning metadata."""

    def __init__(self, buffer: Any, *, batch_size: int,
                 min_items: Optional[int] = None,
                 poll_interval_s: float = 0.01,
                 collate_fn: Optional[Callable[[List[Dict[str, Any]]],
                                               Any]] = None,
                 prefetch: int = 1):
        self._buffer = buffer
        self.batch_size = int(batch_size)
        self.min_items = self.batch_size if min_items is None \
            else int(min_items)
        if not 0 < self.min_items <= self.batch_size:
            # min_items > batch_size would spin forever requesting 0
            raise ValueError(
                f"min_items must be in [1, batch_size={self.batch_size}]"
                f", got {self.min_items}")
        self.poll_interval_s = poll_interval_s
        self._collate = collate_fn
        self._prefetch = max(0, int(prefetch))

    def _pull_batch(self, stop: Optional[threading.Event] = None) -> Any:
        """Accumulate min_items..batch_size rollouts (polling — the
        buffer never blocks its actor loop), then collate."""
        import ray_tpu

        items: List[Dict[str, Any]] = []
        while len(items) < self.min_items:
            if stop is not None and stop.is_set():
                if items:
                    # stopped mid-accumulation: the pops were
                    # destructive, so hand the rollouts back (best
                    # effort — a full buffer genuinely drops them)
                    try:
                        self._buffer.put.remote(items)
                    except Exception:  # noqa: BLE001 — buffer gone
                        pass
                return None
            got = ray_tpu.get(self._buffer.get_batch.remote(
                self.batch_size - len(items)), timeout=60.0)
            items.extend(got)
            if len(items) >= self.min_items:
                break
            time.sleep(self.poll_interval_s)
        return self._collate(items) if self._collate else items

    def iter_batches(self, **_ignored):
        """Endless batch stream with background prefetch: the NEXT
        batch is pulled and collated while the caller computes on the
        current one (the ingestion-overlaps-device-step contract)."""
        import queue as _q

        if self._prefetch == 0:
            while True:
                yield self._pull_batch()
            return
        out: "_q.Queue" = _q.Queue(maxsize=self._prefetch)
        stop = threading.Event()

        def feed():
            try:
                while not stop.is_set():
                    batch = self._pull_batch(stop)
                    if batch is None:
                        return
                    while not stop.is_set():
                        try:
                            out.put(batch, timeout=0.2)
                            break
                        except _q.Full:
                            continue
            except Exception as e:  # noqa: BLE001 — surface via queue
                out.put(e)

        t = threading.Thread(target=feed, daemon=True,
                             name="rollout-prefetch")
        t.start()
        try:
            while True:
                batch = out.get()
                if isinstance(batch, Exception):
                    raise batch
                yield batch
        finally:
            stop.set()

    # Dataset-protocol conveniences (a RolloutShard is its own shard)
    def count(self) -> int:
        import ray_tpu

        return ray_tpu.get(self._buffer.size.remote(), timeout=30.0)


class RolloutStream:
    """The ``datasets={"rollouts": from_rollouts(buffer)}`` object: the
    trainer's ``_shard_datasets`` calls ``streaming_split(world)`` and
    hands each rank one :class:`RolloutShard`."""

    def __init__(self, buffer: Any, *, batch_size: int = 8,
                 min_items: Optional[int] = None,
                 collate_fn: Optional[Callable] = None,
                 prefetch: int = 1):
        self._buffer = buffer
        self._kw = dict(batch_size=batch_size, min_items=min_items,
                        collate_fn=collate_fn, prefetch=prefetch)

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List[RolloutShard]:
        return [RolloutShard(self._buffer, **self._kw) for _ in range(n)]

    @property
    def buffer(self) -> Any:
        return self._buffer


def from_rollouts(buffer: Any, *, batch_size: int = 8,
                  min_items: Optional[int] = None,
                  collate_fn: Optional[Callable] = None,
                  prefetch: int = 1) -> RolloutStream:
    """Expose a :class:`RolloutBuffer` actor to the learner through the
    Data streaming-split contract. `collate_fn(list_of_rollouts)` runs
    on the prefetch thread (padding/packing overlaps the device step
    too); without one, batches are lists of rollout dicts."""
    return RolloutStream(buffer, batch_size=batch_size,
                         min_items=min_items, collate_fn=collate_fn,
                         prefetch=prefetch)
