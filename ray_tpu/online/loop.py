"""OnlineTrainer: the closed loop — learner gang + sampler actors +
rollout buffer, wired through the live weight fabric.

The in-tree example workload is online distillation: the learner (a
``JaxTrainer`` spmd gang reusing ``TrainStep``/gang formation) trains
the model to imitate the completions its OWN samplers generate through
the continuous-batching engine, and publishes refreshed weights every
``publish_every`` steps via ``train.report(publish_weights=...,
weights_delta=True)`` — delta publication ships only the leaves the
optimizer moved, subscriber prefetch pulls them while the engines still
decode the old version, and the hot swap lands between decode ticks. A
positional-embedding freeze (``frozen_leaves``) is both common
distillation practice and what makes the delta path visibly cheaper
than a full publish.

The loop's invariant: sampler staleness stays <= 1 version (each
sampler tracks its high-water mark; ``online_status()`` aggregates it)
while the learner steps continuously — rollout generation, ingestion,
and weight refresh all overlap the device step.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .buffer import RolloutBuffer, from_rollouts
from .sampler import spawn_samplers

_ONLINE_AXES = ("dp", "fsdp", "tp")


@dataclass
class OnlineConfig:
    """Knobs of the online loop (tiny defaults — production runs scale
    num_samplers / batch_size / num_steps, not the structure)."""

    num_samplers: int = 2
    num_steps: int = 16
    batch_size: int = 8
    publish_every: int = 2          # learner steps between publishes
    delta: bool = True              # delta-publish refreshed weights
    # staleness gate: defer a due publish while any sampler still
    # serves an older version than the last one published — the
    # learner keeps stepping at full speed, only the publication
    # cadence adapts, and sampler staleness stays <= 1 by
    # construction. 0 disables the gate; max_publish_skips bounds the
    # deferral so a dead sampler cannot silence publication forever.
    gate_on_staleness: bool = True
    max_publish_skips: int = 50
    buffer_capacity: int = 64
    max_new_tokens: int = 12
    max_prompt_len: int = 8
    sampler_max_batch: int = 2
    sampler_prefetch: bool = True
    learning_rate: float = 1e-3
    weights_name: str = "online"
    # leaves (top-level param keys) excluded from the optimizer — frozen
    # leaves never change, so delta publication skips them
    frozen_leaves: tuple = ("wpe",)
    seed: int = 0


@dataclass
class OnlineResult:
    """What fit() hands back: the learner's Result plus the loop's own
    accounting (per-sampler stats incl. the staleness high-water mark,
    buffer totals, the registry's final listing)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    sampler_stats: List[Dict[str, Any]] = field(default_factory=list)
    buffer_stats: Dict[str, Any] = field(default_factory=dict)
    weight_versions: Dict[str, Any] = field(default_factory=dict)
    max_staleness_versions: Optional[int] = None
    error: Optional[BaseException] = None


def _pad_batch(rollouts: List[Dict[str, Any]], seq_len: int
               ) -> Dict[str, np.ndarray]:
    """Collate rollouts into fixed-shape LM arrays (runs on the
    prefetch thread): tokens = prompt + completion padded to seq_len,
    targets = next token, mask = 1 on completion predictions only (the
    distillation objective imitates the SAMPLED tokens, not the
    prompt)."""
    n = len(rollouts)
    tokens = np.zeros((n, seq_len), np.int32)
    mask = np.zeros((n, seq_len - 1), np.float32)
    versions = np.zeros(n, np.int64)
    for i, r in enumerate(rollouts):
        seq = np.concatenate([r["prompt"], r["completion"]])[:seq_len]
        tokens[i, :len(seq)] = seq
        p = len(r["prompt"])
        # predictions at positions p-1 .. len(seq)-2 produce the
        # completion tokens — that is the imitation region
        mask[i, p - 1:len(seq) - 1] = 1.0
        versions[i] = int(r.get("weights_version") or 0)
    return {"tokens": tokens[:, :-1], "targets": tokens[:, 1:],
            "mask": mask, "versions": versions}


def next_publish_version(name: str) -> int:
    """The version a NEW publisher of `name` should start at:
    publication numbering continues after whatever the registry
    already holds, so a second trainer (or a restarted one) against a
    live weights name never collides with an existing version. Shared
    by OnlineTrainer's initial full publish and the per-tenant
    TenantLoraTrainer (online/lora.py)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called before "
                           "publishing weights")
    return int(w.conductor.call("weights_latest_version", name,
                                timeout=10.0) or 0) + 1


def _distill_loss_fn(model_config) -> Callable:
    """Masked next-token CE over the completion region — the online
    distillation objective (sequence-level: imitate the sampler's
    greedy tokens)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.gpt2 import gpt2_hidden

    def loss_fn(params, batch):
        x = gpt2_hidden(params, batch["tokens"], model_config)
        logits = jnp.dot(x, params["wte"].T,
                         preferred_element_type=jnp.float32)
        logits = logits[..., :model_config.vocab_size]
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, batch["targets"][..., None],
                                 axis=-1)[..., 0]
        mask = batch["mask"]
        return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

    return loss_fn


def _learner_mesh():
    """All local devices on the canonical (dp, fsdp, tp) axes — dp
    carries the data, the model axes collapse to 1 so the GPT-2 spec
    tree reads as replicated."""
    import jax
    from jax.sharding import Mesh

    devs = np.array(jax.devices())
    return Mesh(devs.reshape(len(devs), 1, 1), _ONLINE_AXES)


class OnlineTrainer:
    """Compose a learner gang with N samplers over one weight-fabric
    name and run the online-distillation loop end to end."""

    def __init__(self, model_config: Any = None, *,
                 config: Optional[OnlineConfig] = None,
                 run_config: Any = None,
                 optimizer: Any = None,
                 prompt_fn: Optional[Callable] = None,
                 loss_fn: Optional[Callable] = None):
        if model_config is None:
            import dataclasses

            import jax.numpy as jnp

            from ray_tpu.models.gpt2 import GPT2Config

            model_config = dataclasses.replace(GPT2Config.tiny(),
                                               dtype=jnp.float32)
        self.model_config = model_config
        self.config = config or OnlineConfig()
        self.run_config = run_config
        self.optimizer = optimizer
        self.prompt_fn = prompt_fn
        self.loss_fn = loss_fn

    # ------------------------------------------------------------ pieces

    def _make_optimizer(self):
        if self.optimizer is not None:
            return self.optimizer
        import jax
        import optax

        frozen = tuple(self.config.frozen_leaves)

        def label_fn(params):
            # NB optax.masked would pass the masked-out RAW GRADIENT
            # through to apply_updates — multi_transform + set_to_zero
            # is what actually freezes a leaf (bit-identical across
            # steps, which is what lets delta publication skip it)
            return {k: jax.tree.map(
                lambda _: "freeze" if k in frozen else "train", v)
                for k, v in params.items()}

        return optax.multi_transform(
            {"train": optax.adam(self.config.learning_rate),
             "freeze": optax.set_to_zero()}, label_fn)

    def _seq_len(self) -> int:
        return min(self.model_config.max_seq_len,
                   self.config.max_prompt_len
                   + self.config.max_new_tokens)

    def _model_factory(self):
        """Serializable factory the sampler actors run: template params
        (the sampler's serving layout — single-process default device)
        + the model config."""
        model_config = self.model_config
        seed = self.config.seed

        def factory():
            import jax

            from ray_tpu.models.gpt2 import gpt2_init

            return (gpt2_init(model_config, jax.random.PRNGKey(seed)),
                    model_config)

        return factory

    def _default_prompt_fn(self):
        from .sampler import default_prompt_fn

        return default_prompt_fn(self.model_config.vocab_size,
                                 max_len=self.config.max_prompt_len)

    # --------------------------------------------------------------- fit

    def fit(self) -> OnlineResult:
        import jax

        import ray_tpu
        from ray_tpu import weights as wts
        from ray_tpu.models.gpt2 import gpt2_init
        from ray_tpu.train import JaxTrainer, RunConfig

        cfg = self.config
        model_config = self.model_config
        # the starting point both sides share — published FULL before
        # any sampler exists, so samplers boot onto it. Numbered after
        # whatever the registry already holds under this name (a second
        # fit() against a live cluster must not collide with v1).
        start_version = next_publish_version(cfg.weights_name)
        initial = gpt2_init(model_config, jax.random.PRNGKey(cfg.seed))
        wts.publish(initial, name=cfg.weights_name,
                    version=start_version)
        buffer = ray_tpu.remote(RolloutBuffer).remote(
            cfg.buffer_capacity, name=cfg.weights_name)
        samplers = spawn_samplers(
            cfg.num_samplers, cfg.weights_name, self._model_factory(),
            buffer,
            max_new_tokens=cfg.max_new_tokens,
            max_batch=cfg.sampler_max_batch,
            min_version=start_version,
            prompt_fn=self.prompt_fn or self._default_prompt_fn(),
            prefetch=cfg.sampler_prefetch,
            seed=cfg.seed)
        out = OnlineResult()
        try:
            ray_tpu.get([s.start.remote() for s in samplers],
                        timeout=300.0)
            stream = from_rollouts(
                buffer, batch_size=cfg.batch_size,
                collate_fn=lambda rs, _T=self._seq_len():
                    _pad_batch(rs, _T))
            trainer = JaxTrainer(
                self._train_fn(start_version),
                datasets={"rollouts": stream},
                run_config=self.run_config
                or RunConfig(name=f"online/{cfg.weights_name}"))
            result = trainer.fit()
            out.metrics = result.metrics
            out.metrics_history = result.metrics_history
            out.error = result.error
        finally:
            for s in samplers:
                try:
                    out.sampler_stats.append(ray_tpu.get(
                        s.stop.remote(), timeout=60.0))
                except Exception:  # noqa: BLE001 — sampler died
                    pass
                try:
                    ray_tpu.kill(s)
                except Exception:  # noqa: BLE001
                    pass
            try:
                out.buffer_stats = ray_tpu.get(buffer.stats.remote(),
                                               timeout=30.0)
            except Exception:  # noqa: BLE001
                pass
            try:
                ray_tpu.kill(buffer)
            except Exception:  # noqa: BLE001
                pass
        stale = [s.get("max_staleness_versions")
                 for s in out.sampler_stats
                 if s.get("max_staleness_versions") is not None]
        out.max_staleness_versions = max(stale) if stale else None
        try:
            from ray_tpu.util import state

            out.weight_versions = state.weight_versions(cfg.weights_name)
        except Exception:  # noqa: BLE001 — cluster already down
            pass
        return out

    def _train_fn(self, start_version: int = 1) -> Callable:
        """The learner body (runs under JaxTrainer's session): TrainStep
        over the local mesh, batches pulled from the rollout shard with
        the pull accounted as flight-recorder data_wait, weights
        delta-published every K steps."""
        cfg = self.config
        model_config = self.model_config
        optimizer = self._make_optimizer()
        loss_fn = self.loss_fn or _distill_loss_fn(model_config)
        weights_name = cfg.weights_name

        def train_fn(_tcfg):
            import jax
            from jax.sharding import PartitionSpec as P

            from ray_tpu import train
            from ray_tpu.models.gpt2 import (gpt2_init,
                                             gpt2_partition_specs)
            from ray_tpu.train.trainer import TrainStep

            mesh = _learner_mesh()
            step_fn = TrainStep(
                lambda p, b: loss_fn(p, b), optimizer, mesh,
                gpt2_partition_specs(model_config),
                data_spec=P(("dp", "fsdp")))
            params = gpt2_init(model_config,
                               jax.random.PRNGKey(cfg.seed))
            state = step_fn.init_state(params)
            shard = train.get_dataset_shard("rollouts")
            batches = shard.iter_batches()
            timer = train.get_step_timer()
            ingested_rollouts = 0
            ingested_tokens = 0
            # the initial full publish went out before the samplers
            # spawned; the learner numbers its publications after it
            published = start_version
            publish_due = False
            publish_skips = 0
            ctx = train.get_context()
            for s in range(1, cfg.num_steps + 1):
                with timer.phase("data_wait"):
                    batch = next(batches)
                versions = batch.pop("versions")
                ingested_rollouts += int(versions.shape[0])
                ingested_tokens += int(batch["mask"].sum())
                state, aux = step_fn(state, batch)
                loss = float(aux["loss"])
                _learner_telemetry(
                    ctx, kind="ingest", step=s,
                    rollouts=int(versions.shape[0]),
                    min_version=int(versions.min()),
                    max_version=int(versions.max()))
                metrics = {"step": s, "loss": loss,
                           "ingested_rollouts": ingested_rollouts,
                           "ingested_tokens": ingested_tokens}
                publish_due = publish_due or s % cfg.publish_every == 0
                gated = (publish_due and cfg.gate_on_staleness
                         and publish_skips < cfg.max_publish_skips
                         and not _samplers_caught_up(published,
                                                     weights_name))
                if publish_due and not gated:
                    # versions number PUBLICATIONS consecutively (v1 =
                    # the initial publish), so the staleness gauge
                    # counts publications-behind and the <= 1 invariant
                    # is meaningful; delta ships only the moved leaves
                    train.report(metrics,
                                 publish_weights=state["params"],
                                 weights_name=weights_name,
                                 weights_delta=cfg.delta,
                                 weights_version=published + 1)
                    published += 1
                    publish_due = False
                    publish_skips = 0
                    _learner_telemetry(ctx, kind="publish", step=s,
                                       version=published,
                                       delta=cfg.delta)
                else:
                    if gated:
                        publish_skips += 1
                    train.report(metrics)
                _learner_stats(ctx, steps=s, last_loss=loss,
                               ingested_rollouts=ingested_rollouts,
                               ingested_tokens=ingested_tokens,
                               published_version=published,
                               publish_skips=publish_skips)

        return train_fn


def _samplers_caught_up(last_version: int, weights_name: str,
                        max_age_s: float = 10.0) -> bool:
    """Every live sampler of THIS loop serves `last_version` (or
    newer) — the publication gate's predicate. Only snapshots for this
    weights_name count, and only recent ones from loops still running
    (another loop's samplers — or a dead/errored one's frozen
    snapshot — must not gate this learner). Unreachable conductor or
    no sampler telemetry reads as caught up (the gate must never
    deadlock the learner)."""
    import time

    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return True
    try:
        st = w.conductor.call("get_online_status", timeout=5.0)
    except Exception:  # noqa: BLE001 — conductor mid-restart
        return True
    now = time.time()
    for s in (st.get("samplers") or {}).values():
        if s.get("weights_name") != weights_name:
            continue
        if s.get("run_error") or now - s.get("ts", now) > max_age_s:
            continue
        v = s.get("serving_version")
        if v is not None and v < last_version:
            return False
    return True


def _learner_stats(ctx, **stats) -> None:
    from ray_tpu._private import worker as worker_mod

    from .metrics import online_metrics

    prev = getattr(ctx, "_online_ingested", 0)
    cur = stats.get("ingested_rollouts", prev)
    if cur > prev:
        online_metrics()["ingested_rollouts"].inc(
            cur - prev, tags={"run": ctx.run_id})
    ctx._online_ingested = cur
    w = worker_mod.global_worker
    if w is None:
        return
    try:
        w.conductor.notify(
            "report_online_stats", w.worker_id,
            f"learner/{ctx.run_id}",
            dict(stats, role="learner", run_id=ctx.run_id))
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def _learner_telemetry(ctx, **event) -> None:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return
    try:
        w.conductor.notify("report_online_event",
                           dict(event, run_id=ctx.run_id))
    except Exception:  # noqa: BLE001 — telemetry only
        pass
