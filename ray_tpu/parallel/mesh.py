"""Named-axis device mesh construction.

The reference expresses multi-worker layout with placement groups +
`TPU-v4-8-head`-style resources (python/ray/_private/accelerators/tpu.py:75)
and leaves intra-model parallelism to whatever the user wraps (SURVEY.md
§2.3: only DP exists natively). Here the mesh IS the first-class object:
every parallelism strategy (dp/fsdp/pp/tp/sp/ep) is a named axis of one
`jax.sharding.Mesh`, XLA inserts the collectives, and ICI/DCN placement
falls out of device order (`mesh_utils.create_device_mesh` optimizes
axis-to-torus assignment on real TPU slices).
"""
from __future__ import annotations

from dataclasses import dataclass, field, fields
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis order: data-like axes outermost (cross-slice / DCN friendly),
# model axes innermost (ICI-bandwidth hungry: tp/sp want nearest neighbors).
MESH_AXES: Tuple[str, ...] = ("dp", "fsdp", "pp", "sp", "ep", "tp")


def solve_axis_sizes(vals: Dict[str, int], total: int,
                     unit: str) -> Dict[str, int]:
    """Solve the named-axis size map against `total` items: at most one
    axis may be -1 ("fill with the remainder"), the rest must be
    positive and their product must divide (fill) or equal (no fill)
    `total`. Shared by the ICI solve (MeshConfig.sizes, unit="device")
    and the DCN solve (HybridMeshConfig.dcn_sizes, unit="slice")."""
    vals = dict(vals)
    fill = [k for k, v in vals.items() if v == -1]
    if len(fill) > 1:
        raise ValueError(f"only one axis may be -1, got {fill}")
    fixed = 1
    for k, v in vals.items():
        if v != -1:
            if v <= 0:
                raise ValueError(f"axis {k} must be positive or -1, got {v}")
            fixed *= v
    if fill:
        if total % fixed != 0:
            raise ValueError(
                f"{total} {unit}s not divisible by fixed axes "
                f"product {fixed}")
        vals[fill[0]] = total // fixed
    elif fixed != total:
        raise ValueError(
            f"mesh axes product {fixed} != {unit} count {total}")
    return vals


@dataclass(frozen=True)
class MeshConfig:
    """Sizes for each named axis; -1 on exactly one axis means "fill with
    the remaining devices" (like torch DeviceMesh / GSPMD conventions)."""

    dp: int = -1
    fsdp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    tp: int = 1

    def sizes(self, n_devices: int) -> Dict[str, int]:
        # fields(MeshConfig), not fields(self): subclasses (HybridMeshConfig)
        # add DCN axes that must not leak into the ICI size solve.
        vals = {f.name: getattr(self, f.name) for f in fields(MeshConfig)}
        solved = solve_axis_sizes(vals, n_devices, "device")
        return {k: solved[k] for k in MESH_AXES}

    def build(self, devices: Optional[Sequence[Any]] = None) -> Mesh:
        return make_mesh(self, devices)


def make_mesh(config: Optional[MeshConfig] = None,
              devices: Optional[Sequence[Any]] = None,
              **axis_sizes: int) -> Mesh:
    """Build a `jax.sharding.Mesh` with canonical named axes.

    make_mesh(MeshConfig(dp=2, tp=4))  or  make_mesh(dp=2, tp=4).
    On TPU hardware, device order is topology-optimized so the innermost
    axes land on ICI nearest-neighbor rings.
    """
    if config is None:
        config = MeshConfig(**axis_sizes) if axis_sizes else MeshConfig()
    elif axis_sizes:
        raise ValueError("pass either a MeshConfig or axis kwargs, not both")
    if devices is None:
        devices = jax.devices()
    sizes = config.sizes(len(devices))
    shape = tuple(sizes[a] for a in MESH_AXES)
    return Mesh(ici_device_mesh(shape, devices), MESH_AXES)


def ici_device_mesh(shape: Tuple[int, ...],
                    devices: Sequence[Any]) -> np.ndarray:
    """Topology-optimized device array for one ICI domain (a slice, or the
    whole device set when there is only one). Falls back to a plain
    row-major reshape where mesh_utils has no assignment (virtual CPU
    devices, odd shapes) — shared by make_mesh and the multislice
    per-slice builder."""
    try:
        return mesh_utils.create_device_mesh(
            shape, devices=np.asarray(devices, dtype=object).ravel())
    except (ValueError, AssertionError, NotImplementedError):
        return np.asarray(devices, dtype=object).reshape(shape)


try:  # jax >= 0.6 exports it at top level
    from jax import shard_map as _shard_map_impl
except ImportError:  # pragma: no cover - jax 0.4/0.5
    from jax.experimental.shard_map import shard_map as _shard_map_impl

_REPLICATION_CHECK_KW = next(
    (kw for kw in ("check_vma", "check_rep")
     if kw in __import__("inspect").signature(_shard_map_impl).parameters),
    None)


def validate_axis_names(mesh: Any, specs: Any, what: str = "spec") -> None:
    """Raise a clear ValueError when a PartitionSpec (or pytree of specs)
    names an axis the mesh does not have — instead of the opaque deep-XLA
    failure a bad name produces otherwise. Works for Mesh and
    AbstractMesh alike (anything with .axis_names)."""
    names = tuple(getattr(mesh, "axis_names", ()) or ())
    if not names:
        return
    known = set(names)
    for spec in jax.tree.leaves(specs,
                                is_leaf=lambda s: isinstance(s, P)):
        if not isinstance(spec, P):
            continue
        for entry in tuple(spec):
            axes = entry if isinstance(entry, (tuple, list)) else (entry,)
            for ax in axes:
                if ax is not None and ax not in known:
                    raise ValueError(
                        f"unknown mesh axis {ax!r} in {what} {spec}: "
                        f"this mesh has axes {names} (canonical "
                        f"MESH_AXES = {MESH_AXES})")


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kw):
    """Version-portable `shard_map`: jax renamed the replication-check
    kwarg (check_rep -> check_vma) and moved the function out of
    experimental; this front door accepts `check_vma` and forwards to
    whatever the installed jax calls it. Spec axis names are validated
    against the mesh up front (clear ValueError, not a deep-XLA error)."""
    validate_axis_names(mesh, in_specs, "shard_map in_specs")
    validate_axis_names(mesh, out_specs, "shard_map out_specs")
    if check_vma is not None and _REPLICATION_CHECK_KW:
        kw[_REPLICATION_CHECK_KW] = check_vma
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kw)


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    """Shorthand: named_sharding(mesh, 'dp', None) ==
    NamedSharding(mesh, PartitionSpec('dp', None)). Axis names are
    validated against the mesh up front."""
    pspec = P(*spec)
    validate_axis_names(mesh, pspec, "named_sharding spec")
    return NamedSharding(mesh, pspec)


def host_local_array_to_global(mesh: Mesh, spec: P, host_arrays):
    """Assemble per-host shards into a global jax.Array (multi-host path;
    analog of the reference relying on torch DDP to scatter). Single-host:
    jax.device_put with the target sharding."""
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(host_arrays, sharding)
    return jax.make_array_from_process_local_data(sharding, host_arrays)
