"""ray_tpu.parallel: the TPU-native gang/mesh layer.

This package is the rebuild's replacement for the reference's out-of-band
communication stack (ray.util.collective NCCL groups, torch
ProcessGroupNCCL rendezvous — see /root/reference/python/ray/util/collective/
collective.py and python/ray/train/torch/config.py:64-117): device
collectives are XLA collectives (`psum`, `all_gather`, `ppermute`,
`all_to_all`) compiled over a named `jax.sharding.Mesh` riding ICI within a
slice and DCN across slices; host-side collectives ride the conductor
control plane.

Public surface:
- MeshConfig / make_mesh: named-axis mesh construction (dp/fsdp/pp/tp/sp/ep)
- HybridMeshConfig / make_hybrid_mesh / discover_slice_topology:
  multi-slice DCN x ICI hybrid meshes (data-like axes across slices over
  DCN, model axes within a slice on ICI), with RAY_TPU_VIRTUAL_SLICES
  partitioning the virtual CPU mesh into fake slices for off-silicon tests
- collective: host-level collective group API mirroring
  ray.util.collective's surface (init_collective_group, allreduce, barrier,
  broadcast, allgather, reducescatter, send, recv)
- sharding helpers: named_sharding, with_sharding_constraint shortcuts
"""
from .fsdp import fsdp_shardings, infer_fsdp_specs  # noqa: F401
from .pipeline import (  # noqa: F401
    make_pipeline_fn,
    pipeline_apply,
    stack_stage_params,
)
from .mesh import (  # noqa: F401
    MESH_AXES,
    MeshConfig,
    host_local_array_to_global,
    make_mesh,
    named_sharding,
    shard_map,
)
from .multislice import (  # noqa: F401
    HybridMeshConfig,
    SliceTopology,
    discover_slice_topology,
    make_hybrid_mesh,
)
from .collective import (  # noqa: F401
    CollectiveActorMixin,
    ReduceOp,
    allgather,
    allreduce,
    barrier,
    broadcast,
    create_collective_group,
    destroy_collective_group,
    get_rank,
    get_collective_group_size,
    init_collective_group,
    recv,
    reduce,
    reducescatter,
    send,
)
