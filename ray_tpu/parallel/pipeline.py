"""Pipeline parallelism: GPipe-style microbatch pipeline over the `pp`
mesh axis with `jax.lax.ppermute` activation transfer.

New capability relative to the reference — Ray has no pipeline parallelism
in-tree (SURVEY.md §2.3, §5.7); its role stops at gang-scheduling the
processes that a user-supplied framework pipelines. Here the pipeline is a
first-class functional transform: stage parameters are stacked on a leading
axis and sharded over `pp`, activations circulate around the ICI ring with
`ppermute`, and the whole schedule is one `lax.scan` under `shard_map`, so
XLA overlaps the ring transfer of tick t with the stage compute of tick
t+1 and autodiff through the scan gives pipelined backprop for free.

Schedule: classic GPipe fill-drain. With S stages and M microbatches the
scan runs M + S - 1 ticks; rank 0 feeds microbatch t at tick t, rank S-1
emits microbatch t at tick t + S - 1. Bubble fraction = (S-1)/(M+S-1) —
choose M >= 4*S to keep it under ~20%.

Composes with dp/tp: `make_pipeline_fn` shard_maps over the full mesh, so
the batch stays sharded on ('dp','fsdp') and stage params may carry tp
shardings on their trailing dims.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import shard_map


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x: jax.Array, *,
                   num_microbatches: int,
                   axis_name: str = "pp") -> jax.Array:
    """Run a pipelined forward pass. Call INSIDE shard_map over `axis_name`.

    stage_fn(params_for_one_stage, activation[mb, ...]) -> activation.
    stage_params: this rank's stage parameters (leading stage axis already
    consumed by shard_map).
    x: the full local batch [batch, ...]; it is split into
    `num_microbatches` equal microbatches along axis 0. Every rank receives
    the same x (replicated over `axis_name`); only rank 0's copy is fed in.

    Returns [batch, ...] outputs of the LAST stage, valid on every rank
    (the last stage's outputs are broadcast with a masked psum).
    """
    pp = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    m = num_microbatches
    if x.shape[0] % m != 0:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"num_microbatches {m}")
    mb = x.shape[0] // m
    micro = x.reshape(m, mb, *x.shape[1:])

    # Stages must be shape-preserving across ticks (the usual
    # transformer-layer contract); fold embed/unembed into surrounding code.
    out_shape = jax.eval_shape(stage_fn, stage_params, micro[0])
    if out_shape.shape != micro.shape[1:]:
        raise ValueError(
            "pipeline_apply requires shape-preserving stages "
            f"(input {micro.shape[1:]}, stage output {out_shape.shape}); "
            "fold embed/unembed into the surrounding code")

    state0 = jnp.zeros(out_shape.shape, out_shape.dtype)
    outbuf0 = jnp.zeros((m, *out_shape.shape), out_shape.dtype)
    perm = [(i, (i + 1) % pp) for i in range(pp)]

    def tick(carry, t):
        recv, outbuf = carry
        # rank 0 ingests microbatch t (clamped: ticks past M reuse the
        # last microbatch; their results are never stored)
        feed = micro[jnp.minimum(t, m - 1)].astype(out_shape.dtype)
        inp = jnp.where(rank == 0, feed, recv)
        out = stage_fn(stage_params, inp)
        # last rank stores microbatch t-(pp-1) once the pipe is full
        src = t - (pp - 1)
        valid = (rank == pp - 1) & (src >= 0)
        outbuf = jax.lax.cond(
            valid,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, out, jnp.maximum(src, 0), 0),
            lambda b: b, outbuf)
        recv_next = jax.lax.ppermute(out, axis_name, perm)
        return (recv_next, outbuf), None

    (_, outbuf), _ = jax.lax.scan(
        tick, (state0, outbuf0), jnp.arange(m + pp - 1))
    # broadcast last rank's outputs to all pp ranks
    outbuf = jax.lax.psum(
        jnp.where(rank == pp - 1, outbuf, jnp.zeros_like(outbuf)), axis_name)
    return outbuf.reshape(m * mb, *out_shape.shape[1:])


def stack_stage_params(per_stage_params: Sequence[Any]) -> Any:
    """Stack a list of per-stage param pytrees on a new leading axis, ready
    to shard with PartitionSpec('pp', ...)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage_params)


def make_pipeline_fn(stage_fn: Callable[[Any, jax.Array], jax.Array],
                     mesh: Mesh, *, num_microbatches: int,
                     data_axes=("dp", "fsdp"),
                     param_spec: Optional[Any] = None,
                     axis_name: str = "pp") -> Callable:
    """Build fn(stacked_params, x) -> y shard_mapped over the mesh.

    stacked_params: pytree with leading stage axis of size mesh.shape['pp']
    (see stack_stage_params). x: global batch, sharded on `data_axes`.
    param_spec: optional PartitionSpec pytree for the NON-stage dims of the
    stacked params (e.g. tp shardings); the leading 'pp' axis is prepended.
    """
    from .mesh import validate_axis_names

    validate_axis_names(mesh, P(axis_name, tuple(data_axes)),
                        "pipeline axes")
    if param_spec is not None:
        validate_axis_names(mesh, param_spec, "pipeline param_spec")
    pp = mesh.shape[axis_name]

    def full_param_spec(stacked_params):
        if param_spec is None:
            return jax.tree.map(lambda _: P(axis_name), stacked_params)
        return jax.tree.map(
            lambda s: P(axis_name, *tuple(s)), param_spec,
            is_leaf=lambda s: isinstance(s, P))

    def run(stacked_params, x):
        leading = {np.shape(leaf)[0] if np.ndim(leaf) else None
                   for leaf in jax.tree.leaves(stacked_params)}
        if leading != {pp}:
            raise ValueError(
                f"stacked_params leading (stage) axis must be "
                f"mesh.shape['{axis_name}']={pp}, got {sorted(leading, key=str)}"
                " — did you forget stack_stage_params()?")
        # Validate num_microbatches against the GLOBAL batch HERE, at
        # call time: pipeline_apply's own check only fires inside
        # shard_map, where it surfaces as an opaque trace-depth error
        # naming neither the global batch nor the mesh axes.
        data_sizes = {a: mesh.shape[a] for a in data_axes}
        data_shards = int(np.prod(list(data_sizes.values())))
        global_batch = int(x.shape[0]) if np.ndim(x) else 0
        if global_batch % data_shards != 0:
            raise ValueError(
                f"global batch {global_batch} not divisible by the "
                f"data-axis product {data_shards} (mesh axes "
                f"{data_sizes})")
        local_batch = global_batch // data_shards
        if local_batch % num_microbatches != 0:
            raise ValueError(
                f"num_microbatches={num_microbatches} does not divide "
                f"the per-shard batch {local_batch} (global batch "
                f"{global_batch} over data axes {data_sizes}); choose "
                f"num_microbatches dividing {local_batch}, e.g. by "
                f"sizing the global batch as a multiple of "
                f"{data_shards * num_microbatches}")
        pspec = full_param_spec(stacked_params)
        xspec = P(data_axes)

        def inner(params, xloc):
            # shard_map keeps the stage axis (size 1 locally): squeeze it
            params = jax.tree.map(lambda a: a[0], params)
            return pipeline_apply(
                stage_fn, params, xloc, num_microbatches=num_microbatches,
                axis_name=axis_name)

        return shard_map(
            inner, mesh=mesh,
            in_specs=(pspec, xspec), out_specs=xspec,
            check_vma=False)(stacked_params, x)

    return run


__all__ = ["pipeline_apply", "stack_stage_params", "make_pipeline_fn"]
