"""Multi-slice hybrid meshes: DCN axes across slices, ICI axes within.

SURVEY.md §7 step 4 ("multi-slice = DCN axes"): a TPU pod job spans
several ICI-connected slices stitched together by data-center network.
The literature's recipe (arXiv:2412.14374, arXiv:2011.03641) is to put
data-like parallelism (dp / pp / fsdp-replica) on the slow DCN links and
keep the ICI-bandwidth-hungry axes (tp / sp / ep, intra-slice fsdp) on
the torus. This module makes that a first-class mesh construction:

- `discover_slice_topology()` — which devices belong to which slice,
  from (in priority order) the `RAY_TPU_VIRTUAL_SLICES` override that
  partitions the virtual CPU mesh into fake slices (the whole path is
  unit-testable off-silicon), the devices' own `slice_index` attribute
  (real multislice TPU runtimes), or MEGASCALE env vars.
- `HybridMeshConfig` — `MeshConfig` plus DCN axis sizes (`dcn_dp`,
  `dcn_fsdp`, `dcn_pp`). `build()` lowers to
  `mesh_utils.create_hybrid_device_mesh` on hardware that reports slice
  membership and to a block-assembled equivalent otherwise. The result
  is an ordinary `jax.sharding.Mesh` with the canonical `MESH_AXES`
  names, so pjit specs, FSDP inference, GPipe, and the ops library work
  unchanged on hybrid meshes.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
from jax.sharding import Mesh

from .mesh import (MESH_AXES, MeshConfig, ici_device_mesh,
                   solve_axis_sizes)

# Env override: partition the device set into this many equal contiguous
# fake slices (unit tests / dryruns on the virtual CPU mesh).
VIRTUAL_SLICES_ENV = "RAY_TPU_VIRTUAL_SLICES"

# Mesh axes that may span DCN, mapped to their HybridMeshConfig field.
# dp/pp are the classic cross-slice axes; dcn_fsdp expresses the
# "replicate the FSDP shard group per slice" layout (zero-3 inside a
# slice, gradient allreduce across slices).
DCN_AXES: Dict[str, str] = {"dp": "dcn_dp", "fsdp": "dcn_fsdp",
                            "pp": "dcn_pp"}


@dataclass(frozen=True)
class SliceTopology:
    """Slice membership of a device set. `slices[i]` is the device list
    of slice i in DCN order; every slice has the same device count."""

    slices: Tuple[Tuple[Any, ...], ...]
    source: str  # "virtual" | "slice_index" | "megascale" | "single"

    @property
    def num_slices(self) -> int:
        return len(self.slices)

    @property
    def devices_per_slice(self) -> int:
        return len(self.slices[0]) if self.slices else 0

    @property
    def devices(self) -> List[Any]:
        return [d for s in self.slices for d in s]

    def describe(self) -> Dict[str, Any]:
        return {"num_slices": self.num_slices,
                "devices_per_slice": self.devices_per_slice,
                "source": self.source}


def _partition(devices: Sequence[Any], k: int,
               source: str) -> SliceTopology:
    n = len(devices)
    if k <= 0:
        raise ValueError(f"slice count must be positive, got {k}")
    if n % k != 0:
        raise ValueError(
            f"{n} devices do not partition into {k} equal slices")
    per = n // k
    return SliceTopology(
        slices=tuple(tuple(devices[i * per:(i + 1) * per])
                     for i in range(k)),
        source=source)


def discover_slice_topology(
        devices: Optional[Sequence[Any]] = None) -> SliceTopology:
    """Detect slice count/membership for `devices` (default: all).

    Priority: RAY_TPU_VIRTUAL_SLICES override > per-device `slice_index`
    (real multislice TPU runtimes) > MEGASCALE_NUM_SLICES env > single
    slice. Devices within a slice keep their given order; slices are
    ordered by slice id (or by position for the contiguous partitions).
    """
    if devices is None:
        import jax

        devices = jax.devices()
    devices = list(devices)

    override = os.environ.get(VIRTUAL_SLICES_ENV)
    if override:
        return _partition(devices, int(override), "virtual")

    by_slice: Dict[int, List[Any]] = {}
    have_slice_index = bool(devices)
    for d in devices:
        idx = getattr(d, "slice_index", None)
        if idx is None:
            have_slice_index = False
            by_slice = {}
            break
        by_slice.setdefault(int(idx), []).append(d)
    if have_slice_index:
        # The devices carry their own slice identity — trust it even
        # when single-valued: MEGASCALE_NUM_SLICES in the env must not
        # partition what the runtime says is ONE ICI slice (e.g.
        # jax.local_devices() on a multislice worker).
        sizes = {len(v) for v in by_slice.values()}
        if len(sizes) != 1:
            raise ValueError(
                f"unequal slice sizes from slice_index: "
                f"{ {k: len(v) for k, v in by_slice.items()} }")
        return SliceTopology(
            slices=tuple(tuple(by_slice[k]) for k in sorted(by_slice)),
            source="slice_index" if len(by_slice) > 1 else "single")

    megascale = os.environ.get("MEGASCALE_NUM_SLICES")
    if megascale and int(megascale) > 1:
        return _partition(devices, int(megascale), "megascale")

    return SliceTopology(slices=(tuple(devices),), source="single")


@dataclass(frozen=True)
class HybridMeshConfig(MeshConfig):
    """MeshConfig plus DCN axis sizes. The base fields size the ICI mesh
    WITHIN one slice (same -1 fill convention, solved against the
    per-slice device count); dcn_* size the slice grid (at most one may
    be -1 to fill with the remaining slices). The final mesh axis `a`
    has size dcn_a * ici_a, DCN-major — cross-slice neighbors are the
    outer blocks of the axis, exactly like
    `mesh_utils.create_hybrid_device_mesh`."""

    dcn_dp: int = 1
    dcn_fsdp: int = 1
    dcn_pp: int = 1

    def dcn_sizes(self, num_slices: int) -> Dict[str, int]:
        vals = {axis: getattr(self, f) for axis, f in DCN_AXES.items()}
        try:
            solved = solve_axis_sizes(vals, num_slices, "slice")
        except ValueError as e:
            raise ValueError(f"DCN axes: {e}") from None
        return {a: solved.get(a, 1) for a in MESH_AXES}

    def build(self, devices: Optional[Sequence[Any]] = None,
              topology: Optional[SliceTopology] = None) -> Mesh:
        return make_hybrid_mesh(self, devices=devices, topology=topology)


def make_hybrid_mesh(config: HybridMeshConfig,
                     devices: Optional[Sequence[Any]] = None,
                     topology: Optional[SliceTopology] = None) -> Mesh:
    """Build the DCN x ICI hybrid `Mesh` for `config`.

    Single-slice degradation: when discovery finds one slice but the
    config asks for DCN axes, the whole request collapses onto ICI (a
    dev box IS one slice) — the merged flat mesh has identical axis
    sizes and named-axis semantics, so programs written for the hybrid
    layout run unchanged.
    """
    if topology is None:
        topology = discover_slice_topology(devices)
    elif devices is not None and set(topology.devices) != set(devices):
        raise ValueError(
            "topology does not cover the given devices: the explicit "
            "SliceTopology must be built from exactly the same device "
            "set")
    devices = topology.devices

    if topology.num_slices == 1:
        ici = config.sizes(len(devices) // _dcn_product(config))
        dcn = {a: getattr(config, DCN_AXES[a], 1) if a in DCN_AXES else 1
               for a in MESH_AXES}
        merged = MeshConfig(**{a: ici[a] * max(1, dcn[a])
                               for a in MESH_AXES})
        return merged.build(devices)

    ici = config.sizes(topology.devices_per_slice)
    dcn = config.dcn_sizes(topology.num_slices)
    ici_shape = tuple(ici[a] for a in MESH_AXES)
    dcn_shape = tuple(dcn[a] for a in MESH_AXES)

    if topology.source == "slice_index":
        # real multislice runtime: let mesh_utils optimize both levels
        try:
            from jax.experimental import mesh_utils

            dev_array = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape,
                devices=np.asarray(devices, dtype=object).ravel())
            return Mesh(dev_array, MESH_AXES)
        except (ValueError, AssertionError, NotImplementedError,
                AttributeError):
            pass  # fall through to the block assembly

    return Mesh(_assemble_hybrid(topology, ici_shape, dcn_shape),
                MESH_AXES)


def dcn_axis_factors(config: MeshConfig, n_devices: int,
                     num_slices: int) -> Dict[str, int]:
    """Per-axis DCN span of `config` laid out over `num_slices` equal
    slices: factor d means a line along that mesh axis touches d distinct
    slices (d-1 of every d hops ride DCN, not ICI). Hybrid configs get
    their declared dcn_* sizes; a FLAT MeshConfig stretched across a
    multi-slice device set gets a stride analysis of the row-major layout
    — this is how the analyzer catches tp/sp/ep silently spanning DCN.
    """
    if num_slices <= 1:
        return {a: 1 for a in MESH_AXES}
    if isinstance(config, HybridMeshConfig):
        return config.dcn_sizes(num_slices)
    # Exact count on the row-major layout: map every device position to
    # its (contiguous) slice and count distinct slices along each axis's
    # lines — no alignment assumptions, so layouts whose lines straddle
    # a slice boundary (e.g. dp=3 x tp=2 over 2 slices) are caught too.
    sizes = config.sizes(n_devices)
    per_slice = n_devices // num_slices
    shape = tuple(sizes[a] for a in MESH_AXES)
    slice_ids = (np.arange(n_devices) // per_slice).reshape(shape)
    factors: Dict[str, int] = {}
    for i, a in enumerate(MESH_AXES):
        if shape[i] <= 1:
            factors[a] = 1
            continue
        lines = np.moveaxis(slice_ids, i, -1).reshape(-1, shape[i])
        factors[a] = int(max(len(set(line)) for line in lines))
    return factors


def _dcn_product(config: HybridMeshConfig) -> int:
    p = 1
    for f in DCN_AXES.values():
        v = getattr(config, f)
        p *= v if v > 0 else 1
    return max(1, p)


def _assemble_hybrid(topology: SliceTopology,
                     ici_shape: Tuple[int, ...],
                     dcn_shape: Tuple[int, ...]) -> np.ndarray:
    """Block-assemble the hybrid device array: each slice becomes one
    ICI-shaped block, placed at its DCN grid coordinate (DCN-major on
    every axis). Mirrors create_hybrid_device_mesh for device sets that
    carry no slice_index (virtual slices, env-discovered topologies)."""
    final_shape = tuple(d * i for d, i in zip(dcn_shape, ici_shape))
    slice_grid = np.arange(topology.num_slices).reshape(dcn_shape)
    full = np.empty(final_shape, dtype=object)
    for coord in np.ndindex(*dcn_shape):
        block = ici_device_mesh(ici_shape,
                                topology.slices[int(slice_grid[coord])])
        full[tuple(slice(c * i, (c + 1) * i)
                   for c, i in zip(coord, ici_shape))] = block
    return full


__all__ = [
    "DCN_AXES",
    "HybridMeshConfig",
    "dcn_axis_factors",
    "SliceTopology",
    "VIRTUAL_SLICES_ENV",
    "discover_slice_topology",
    "make_hybrid_mesh",
]
