"""FSDP sharding-rule inference: ZeRO-3-style parameter sharding as
PartitionSpecs on the `fsdp` mesh axis.

The reference passes FSDP through to torch (train/torch/train_loop_utils.py
supports FSDP wrap; SURVEY.md §2.3) — wrapping, gathering and
resharding are imperative torch-side work. On TPU the same semantics are
one sharding annotation: shard each parameter's largest eligible dim on
`fsdp`, and XLA's SPMD partitioner inserts the all-gather before use and
reduce-scatter of grads — the ZeRO-3 schedule — automatically. Optimizer
state inherits the param layout through TrainStep.init_state.
"""
from __future__ import annotations

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def infer_fsdp_specs(params: Any, fsdp_size: int, *,
                     base_specs: Optional[Any] = None,
                     axis_name: str = "fsdp",
                     min_size_to_shard: int = 2 ** 16) -> Any:
    """PartitionSpec pytree sharding each param's largest free dim on
    `axis_name`.

    base_specs: existing spec tree (e.g. tp shardings from the model) to
    compose with — fsdp takes the largest dim not already sharded and
    divisible by fsdp_size. Leaves smaller than `min_size_to_shard`
    elements stay replicated (gather cost would beat the memory win).
    """
    if base_specs is None:
        base_specs = jax.tree.map(lambda x: P(*([None] * np.ndim(x))),
                                  params)

    def leaf_spec(x, spec: P) -> P:
        shape = np.shape(x)
        spec = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
        if fsdp_size <= 1 or np.size(x) < min_size_to_shard:
            return P(*spec)
        # a spec may use each mesh axis at most once: if the base spec
        # already shards some dim on `axis_name` (alone or inside a
        # tuple), adding it again would be a duplicate-axis error
        used = {a for s in spec if s is not None
                for a in (s if isinstance(s, tuple) else (s,))}
        if axis_name in used:
            return P(*spec)
        cand = [i for i, (dim, s) in enumerate(zip(shape, spec))
                if s is None and dim % fsdp_size == 0]
        if not cand:
            return P(*spec)
        best = max(cand, key=lambda i: shape[i])
        new = list(spec)
        new[best] = axis_name
        return P(*new)

    return jax.tree.map(leaf_spec, params, base_specs,
                        is_leaf=lambda x: isinstance(x, P))


def fsdp_shardings(mesh: Mesh, params: Any, **kw) -> Any:
    """NamedSharding tree for `params` on `mesh` (see infer_fsdp_specs)."""
    axis = kw.get("axis_name", "fsdp")
    from .mesh import validate_axis_names

    validate_axis_names(mesh, P(axis), "fsdp_shardings axis_name")
    specs = infer_fsdp_specs(params, mesh.shape.get(axis, 1), **kw)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda s: isinstance(s, P))


__all__ = ["infer_fsdp_specs", "fsdp_shardings"]
