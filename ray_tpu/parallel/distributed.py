"""Multi-host mesh rendezvous: conductor-KV-driven jax.distributed init.

The reference bootstraps its data plane with a NCCL rendezvous
(MASTER_ADDR + torch dist.init_process_group — train/torch/config.py:64-117);
the TPU-native equivalent is `jax.distributed.initialize(coordinator,
num_processes, process_id)`, after which every process sees the GLOBAL
device set and a single jitted SPMD program spans hosts with XLA
collectives over ICI/DCN (SURVEY.md §5.8, §7 step 4).

Rank 0 picks a free port on its host, publishes `host:port` under a
group key in the conductor KV; other ranks poll the key. This is the
same pattern as the reference's `NCCLUniqueIDStore` named actor
(util/collective/collective_group/nccl_collective_group.py:28-50), minus
the actor: the KV is already the cluster's rendezvous plane.
"""
from __future__ import annotations

import json
import logging
import os
import socket
import time
from typing import Callable, Dict, List, Optional, Tuple

_NAMESPACE = "_jax_distributed"


def _free_port(host: str) -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _local_ip(peer_host: str = "8.8.8.8") -> str:
    """Best-effort address other hosts can reach us on: the source IP of
    the route to `peer_host`. Pass the conductor's host — gang members
    must reach each other on the network they reach the head on (a
    public-internet probe can return an unroutable interface)."""
    env = os.environ.get("RAY_TPU_NODE_IP")
    if env:
        return env
    if peer_host in ("127.0.0.1", "localhost", "::1"):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((peer_host, 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def rendezvous_coordinator(kv_put: Callable, kv_get: Callable,
                           group_key: str, rank: int,
                           timeout: float = 120.0,
                           host: Optional[str] = None) -> str:
    """Agree on a coordinator address for a worker gang. Rank 0 claims
    it; everyone returns `host:port`."""
    key = f"{group_key}/coordinator".encode()
    if rank == 0:
        host = host or _local_ip()
        addr = f"{host}:{_free_port('0.0.0.0')}"
        kv_put(key, addr.encode(), namespace=_NAMESPACE)
        return addr
    deadline = time.monotonic() + timeout
    sleep = 0.01
    while time.monotonic() < deadline:
        got = kv_get(key, namespace=_NAMESPACE)
        if got:
            return got.decode()
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.5)
    raise TimeoutError(f"no coordinator published for {group_key} "
                       f"within {timeout}s")


# ------------------------------------------------------ slice rendezvous

def detect_slice_id() -> Optional[int]:
    """This process's TPU slice id from the runtime env, or None when no
    slice identity is advertised (single-slice / non-megascale jobs).
    RAY_TPU_SLICE_ID is the explicit override; MEGASCALE_SLICE_ID is what
    the multislice TPU runtime exports on every worker VM."""
    for var in ("RAY_TPU_SLICE_ID", "MEGASCALE_SLICE_ID"):
        v = os.environ.get(var)
        if v is not None and v != "":
            return int(v)
    return None


def rendezvous_slices(kv_put: Callable, kv_get: Callable, group_key: str,
                      rank: int, world: int, slice_id: Optional[int],
                      timeout: float = 120.0
                      ) -> Optional[Dict[int, List[int]]]:
    """Each rank publishes its slice id (or a "none" marker) under the
    group key; rank 0 polls the per-rank keys, assembles the slice map
    {slice_id: sorted ranks}, and publishes it under one assembled key
    that the other ranks poll — O(world) conductor RPCs total instead of
    every rank polling every other rank. Same KV-rendezvous pattern as
    the coordinator claim above — the conductor KV is the cluster's
    rendezvous plane.

    Slice identity must be all-or-none across the gang: mixed
    some-ranks-have-a-slice-id gangs (env leak, heterogeneous hosts)
    raise ValueError on EVERY rank instead of deadlocking with
    mismatched process ids. Returns None when no rank has a slice id
    (single-slice gang, no grouping needed)."""
    kv_put(f"{group_key}/slice/{rank}".encode(),
           ("none" if slice_id is None else str(int(slice_id))).encode(),
           namespace=_NAMESPACE)
    assembled_key = f"{group_key}/slice_assembled".encode()
    deadline = time.monotonic() + timeout
    sleep = 0.01

    if rank != 0:
        while True:
            v = kv_get(assembled_key, namespace=_NAMESPACE)
            if v:
                rec = json.loads(v.decode())
                if "__error__" in rec:
                    raise ValueError(rec["__error__"])
                if not rec:
                    return None
                return {int(s): rs for s, rs in sorted(rec.items())}
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"slice rendezvous for {group_key}: rank 0 did not "
                    f"publish the assembled slice map within {timeout}s")
            time.sleep(sleep)
            sleep = min(sleep * 2, 0.5)

    got: Dict[int, Optional[int]] = {rank: slice_id}
    while len(got) < world:
        for r in range(world):
            if r in got:
                continue
            v = kv_get(f"{group_key}/slice/{r}".encode(),
                       namespace=_NAMESPACE)
            if v:
                s = v.decode()
                got[r] = None if s == "none" else int(s)
        if len(got) < world:
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"slice rendezvous for {group_key}: only "
                    f"{len(got)}/{world} ranks published within "
                    f"{timeout}s")
            time.sleep(sleep)
            sleep = min(sleep * 2, 0.5)

    missing = sorted(r for r, s in got.items() if s is None)
    if missing:
        if len(missing) < world:
            msg = (f"inconsistent slice identity in {group_key}: ranks "
                   f"{missing} have no slice id while the rest do — "
                   f"slice identity must be all-or-none across the gang")
            kv_put(assembled_key, json.dumps({"__error__": msg}).encode(),
                   namespace=_NAMESPACE)
            raise ValueError(msg)
        kv_put(assembled_key, b"{}", namespace=_NAMESPACE)
        return None

    slice_map: Dict[int, List[int]] = {}
    for r, s in got.items():
        slice_map.setdefault(s, []).append(r)
    slice_map = {s: sorted(rs) for s, rs in sorted(slice_map.items())}
    kv_put(assembled_key,
           json.dumps({str(s): rs for s, rs in slice_map.items()}).encode(),
           namespace=_NAMESPACE)
    return slice_map


def publish_slice_map(kv_put: Callable, group_key: str,
                      slice_map: Dict[int, List[int]],
                      process_ids: Dict[int, int], world: int) -> None:
    """Write the gang's slice map under `{group_key}/slice_map` where
    `ray_tpu.util.state.slice_topology` reads it (rank 0 only)."""
    kv_put(f"{group_key}/slice_map".encode(),
           json.dumps({"slices": {str(s): rs
                                  for s, rs in slice_map.items()},
                       "process_ids": {str(r): p
                                       for r, p in process_ids.items()},
                       "world": world}).encode(),
           namespace=_NAMESPACE)


def slice_process_ids(slice_map: Dict[int, List[int]]) -> Dict[int, int]:
    """Slice-major process-id assignment: ranks of the same slice get
    CONTIGUOUS process ids (what `mesh_utils.create_hybrid_device_mesh`
    with process-granules and the DCN-outer axis order expect), with
    rank 0's slice first so rank 0 keeps process id 0 — it hosts the
    jax.distributed coordinator service."""
    rank0_slice = next(s for s, rs in slice_map.items() if 0 in rs)
    order = sorted(slice_map, key=lambda s: (s != rank0_slice, s))
    pids: Dict[int, int] = {}
    pid = 0
    for s in order:
        for r in sorted(slice_map[s]):
            pids[r] = pid
            pid += 1
    return pids


def initialize_jax_distributed(group_key: str, rank: int, world: int,
                               kv_put: Optional[Callable] = None,
                               kv_get: Optional[Callable] = None,
                               timeout: float = 120.0,
                               host: Optional[str] = None,
                               slice_id: Optional[int] = None,
                               ) -> Optional[Dict[str, object]]:
    """Run the coordinator rendezvous and `jax.distributed.initialize`.

    Must be called before any other jax API touches the backend. With
    world == 1 this is a no-op (single-process SPMD needs no service).
    kv_put/kv_get default to the connected cluster's conductor KV.

    With `slice_id` (explicit, or detected from the runtime env by the
    caller via `detect_slice_id`), ranks first rendezvous their slice
    membership: process ids are reassigned slice-major so processes of
    one slice are contiguous in the jax.distributed job, and rank 0
    publishes the slice map under `{group_key}/slice_map` where the
    state API (`ray_tpu.util.state.slice_topology`) finds it. Returns
    the slice info dict ({"slice_id", "slices", "process_ids"}) when a
    slice rendezvous ran, else None.
    """
    if world <= 1:
        return None
    if kv_put is None or kv_get is None:
        from .._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError(
                "initialize_jax_distributed needs a connected ray_tpu "
                "worker (or explicit kv_put/kv_get)")
        kv_put = lambda k, v, namespace: w.conductor.call(  # noqa: E731
            "kv_put", k, v, True, namespace, timeout=10.0)
        kv_get = lambda k, namespace: w.conductor.call(  # noqa: E731
            "kv_get", k, namespace, timeout=10.0)
        if host is None:
            # advertise on the interface that reaches the conductor
            host = _local_ip(w.conductor_address[0])

    process_id = rank
    slice_info: Optional[Dict[str, object]] = None
    # Always rendezvous (slice_id may be None): slice identity must be
    # all-or-none across the gang, and only the rendezvous can tell this
    # rank whether the OTHERS have one — a mixed gang fails fast with a
    # clear error on every rank instead of deadlocking on mismatched
    # process ids.
    slice_map = rendezvous_slices(kv_put, kv_get, group_key, rank,
                                  world, slice_id, timeout)
    if slice_map is not None:
        pids = slice_process_ids(slice_map)
        process_id = pids[rank]
        slice_info = {"slice_id": int(slice_id),
                      "slices": {int(s): rs
                                 for s, rs in slice_map.items()},
                      "process_ids": {int(r): p
                                      for r, p in pids.items()}}
        if rank == 0:
            publish_slice_map(kv_put, group_key, slice_map, pids, world)

    coordinator = rendezvous_coordinator(kv_put, kv_get, group_key, rank,
                                         timeout, host=host)
    import jax

    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu" or \
            getattr(jax.config, "jax_platforms", None) == "cpu":
        # CPU-pinned gangs (tests, host-side data/eval work): the
        # default CPU client has no cross-process collectives ("not
        # implemented on the CPU backend"); gloo is jaxlib's portable
        # implementation. Best-effort — older jaxlibs without the
        # option still form the gang for non-collective work.
        try:
            jax.config.update("jax_cpu_collectives_implementation",
                              "gloo")
        except Exception:  # noqa: BLE001 — option absent on this jax
            pass
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=world,
                               process_id=process_id)
    return slice_info


def is_jax_distributed_initialized() -> bool:
    """True once jax.distributed.initialize succeeded in this process.

    Version-portable: `jax.distributed.is_initialized` only exists on
    newer jax; older 0.4.x exposes nothing public, so fall back to the
    internal global_state's client handle (None until initialize)."""
    import jax

    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src import distributed as _dist

        state = getattr(_dist, "global_state", None)
        return state is not None and \
            getattr(state, "client", None) is not None
    except ImportError:
        return False


def setup_jax_distributed(timeout: float = 120.0) -> Tuple[int, int]:
    """Inside a JaxTrainer(mode="workers") train_fn: rendezvous this
    worker gang into one jax.distributed job and return (rank, world).

    JaxTrainer performs this automatically before train_fn when
    ScalingConfig.setup_jax_distributed (the default) — calling it again
    is a no-op, so train_fns written for older versions keep working.

    After this returns, `jax.devices()` is the GLOBAL device set across
    all gang workers; build a Mesh over it (parallel.make_mesh) and jit
    normally — the reference's prepare_model/DDP step
    (train_loop_utils.py:158) has no equivalent here because XLA owns
    gradient reduction.
    """
    from ..train.session import get_context

    ctx = get_context()
    if not is_jax_distributed_initialized():
        group_key = getattr(ctx, "jax_dist_key", None) or \
            f"group/{ctx.experiment_name}"
        # slice identity: the runtime env (MEGASCALE_SLICE_ID) is ground
        # truth when present — gang placement does not guarantee host
        # order follows physical slice boundaries, so the trainer's
        # rank-arithmetic assignment (ScalingConfig.num_slices) is only
        # the fallback for runtimes that advertise no slice identity.
        detected = detect_slice_id()
        assigned = getattr(ctx, "slice_id", None)
        slice_id = detected if detected is not None else assigned
        if detected is not None and assigned is not None and \
                detected != assigned:
            logging.getLogger(__name__).warning(
                "rank %d: trainer assigned slice %s but the TPU runtime "
                "reports slice %s; using the runtime's value",
                ctx.rank, assigned, detected)
        info = initialize_jax_distributed(group_key, ctx.rank,
                                          ctx.world_size, timeout=timeout,
                                          slice_id=slice_id)
        if info is not None:
            ctx.slice_map = info["slices"]
    return ctx.rank, ctx.world_size
