"""Multi-host mesh rendezvous: conductor-KV-driven jax.distributed init.

The reference bootstraps its data plane with a NCCL rendezvous
(MASTER_ADDR + torch dist.init_process_group — train/torch/config.py:64-117);
the TPU-native equivalent is `jax.distributed.initialize(coordinator,
num_processes, process_id)`, after which every process sees the GLOBAL
device set and a single jitted SPMD program spans hosts with XLA
collectives over ICI/DCN (SURVEY.md §5.8, §7 step 4).

Rank 0 picks a free port on its host, publishes `host:port` under a
group key in the conductor KV; other ranks poll the key. This is the
same pattern as the reference's `NCCLUniqueIDStore` named actor
(util/collective/collective_group/nccl_collective_group.py:28-50), minus
the actor: the KV is already the cluster's rendezvous plane.
"""
from __future__ import annotations

import os
import socket
import time
from typing import Callable, Optional, Tuple

_NAMESPACE = "_jax_distributed"


def _free_port(host: str) -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        s.bind((host, 0))
        return s.getsockname()[1]
    finally:
        s.close()


def _local_ip(peer_host: str = "8.8.8.8") -> str:
    """Best-effort address other hosts can reach us on: the source IP of
    the route to `peer_host`. Pass the conductor's host — gang members
    must reach each other on the network they reach the head on (a
    public-internet probe can return an unroutable interface)."""
    env = os.environ.get("RAY_TPU_NODE_IP")
    if env:
        return env
    if peer_host in ("127.0.0.1", "localhost", "::1"):
        return "127.0.0.1"
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect((peer_host, 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


def rendezvous_coordinator(kv_put: Callable, kv_get: Callable,
                           group_key: str, rank: int,
                           timeout: float = 120.0,
                           host: Optional[str] = None) -> str:
    """Agree on a coordinator address for a worker gang. Rank 0 claims
    it; everyone returns `host:port`."""
    key = f"{group_key}/coordinator".encode()
    if rank == 0:
        host = host or _local_ip()
        addr = f"{host}:{_free_port('0.0.0.0')}"
        kv_put(key, addr.encode(), namespace=_NAMESPACE)
        return addr
    deadline = time.monotonic() + timeout
    sleep = 0.01
    while time.monotonic() < deadline:
        got = kv_get(key, namespace=_NAMESPACE)
        if got:
            return got.decode()
        time.sleep(sleep)
        sleep = min(sleep * 2, 0.5)
    raise TimeoutError(f"no coordinator published for {group_key} "
                       f"within {timeout}s")


def initialize_jax_distributed(group_key: str, rank: int, world: int,
                               kv_put: Optional[Callable] = None,
                               kv_get: Optional[Callable] = None,
                               timeout: float = 120.0,
                               host: Optional[str] = None) -> None:
    """Run the coordinator rendezvous and `jax.distributed.initialize`.

    Must be called before any other jax API touches the backend. With
    world == 1 this is a no-op (single-process SPMD needs no service).
    kv_put/kv_get default to the connected cluster's conductor KV.
    """
    if world <= 1:
        return
    if kv_put is None or kv_get is None:
        from .._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            raise RuntimeError(
                "initialize_jax_distributed needs a connected ray_tpu "
                "worker (or explicit kv_put/kv_get)")
        kv_put = lambda k, v, namespace: w.conductor.call(  # noqa: E731
            "kv_put", k, v, True, namespace, timeout=10.0)
        kv_get = lambda k, namespace: w.conductor.call(  # noqa: E731
            "kv_get", k, namespace, timeout=10.0)
        if host is None:
            # advertise on the interface that reaches the conductor
            host = _local_ip(w.conductor_address[0])

    coordinator = rendezvous_coordinator(kv_put, kv_get, group_key, rank,
                                         timeout, host=host)
    import jax

    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=world, process_id=rank)


def is_jax_distributed_initialized() -> bool:
    """True once jax.distributed.initialize succeeded in this process."""
    import jax

    return jax.distributed.is_initialized()


def setup_jax_distributed(timeout: float = 120.0) -> Tuple[int, int]:
    """Inside a JaxTrainer(mode="workers") train_fn: rendezvous this
    worker gang into one jax.distributed job and return (rank, world).

    JaxTrainer performs this automatically before train_fn when
    ScalingConfig.setup_jax_distributed (the default) — calling it again
    is a no-op, so train_fns written for older versions keep working.

    After this returns, `jax.devices()` is the GLOBAL device set across
    all gang workers; build a Mesh over it (parallel.make_mesh) and jit
    normally — the reference's prepare_model/DDP step
    (train_loop_utils.py:158) has no equivalent here because XLA owns
    gradient reduction.
    """
    from ..train.session import get_context

    ctx = get_context()
    if not is_jax_distributed_initialized():
        group_key = getattr(ctx, "jax_dist_key", None) or \
            f"group/{ctx.experiment_name}"
        initialize_jax_distributed(group_key, ctx.rank, ctx.world_size,
                                   timeout=timeout)
    return ctx.rank, ctx.world_size
