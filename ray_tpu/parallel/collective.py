"""Host-level collective groups over the conductor control plane.

API surface mirrors the reference's ray.util.collective
(/root/reference/python/ray/util/collective/collective.py —
init_collective_group :120, create_collective_group :151, allreduce :258,
barrier :298, reduce :329, broadcast :373, allgather :423, reducescatter
:472, send :531, recv :594). The reference backs these with cupy-NCCL /
pygloo groups rendezvoused through a named NCCLUniqueIDStore actor
(collective_group/nccl_collective_group.py:28-50).

TPU-native split (SURVEY.md §5.8): tensors that live on device move inside
jitted programs via XLA collectives over ICI/DCN — there is no out-of-band
device channel to manage. What remains for a host API is *small host-side
state* (metrics, rendezvous payloads, eval aggregates), so the backend here
is the conductor's KV store: every rank in a group executes the same
sequence of collective calls; per-call sequence numbers key the KV slots,
rank 0 performs reductions, and slots are acknowledged + garbage-collected.
This trades bandwidth for zero extra moving parts — exactly right for the
control-plane payloads this API is for.
"""
from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

_NS = "collective"
_POLL_S = 0.002


class ReduceOp:
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"


_REDUCERS = {
    ReduceOp.SUM: lambda xs: _tree_reduce(np.add, xs),
    ReduceOp.PRODUCT: lambda xs: _tree_reduce(np.multiply, xs),
    ReduceOp.MIN: lambda xs: _tree_reduce(np.minimum, xs),
    ReduceOp.MAX: lambda xs: _tree_reduce(np.maximum, xs),
}


def _tree_reduce(op, xs: List[Any]):
    out = xs[0]
    for x in xs[1:]:
        out = op(out, x)
    return out


def _kv():
    from .._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError("ray_tpu.init() must be called before collectives")
    return w.conductor


def _put(key: str, value: Any) -> None:
    _kv().call("kv_put", key.encode(), pickle.dumps(value, protocol=5), True,
               _NS, timeout=None)


def _get_blocking(key: str, timeout: Optional[float] = None) -> Any:
    deadline = None if timeout is None else time.monotonic() + timeout
    kv = _kv()
    poll = _POLL_S
    while True:
        raw = kv.call("kv_get", key.encode(), _NS, timeout=None)
        if raw is not None:
            return pickle.loads(raw)
        if deadline is not None and time.monotonic() > deadline:
            raise TimeoutError(f"collective key {key} not produced in time")
        time.sleep(poll)
        poll = min(poll * 1.5, 0.05)


def _del(key: str) -> None:
    _kv().call("kv_del", key.encode(), _NS, timeout=None)


@dataclass
class _Group:
    name: str
    world_size: int
    rank: int
    seq: int = 0
    p2p_seq: Dict[tuple, int] = field(default_factory=dict)

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq


_groups: Dict[str, _Group] = {}
_groups_lock = threading.Lock()


def init_collective_group(world_size: int, rank: int,
                          backend: str = "kv",
                          group_name: str = "default") -> None:
    """Join `group_name` as `rank` of `world_size` (reference
    collective.py:120). Blocks until every rank has joined."""
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world {world_size}")
    with _groups_lock:
        if group_name in _groups:
            raise RuntimeError(f"group {group_name!r} already initialized")
        _groups[group_name] = _Group(group_name, world_size, rank)
    _put(f"{group_name}/join/{rank}", True)
    for r in range(world_size):
        _get_blocking(f"{group_name}/join/{r}")


def create_collective_group(actors, world_size: int, ranks: List[int],
                            backend: str = "kv",
                            group_name: str = "default"):
    """Declarative variant (reference collective.py:151): tell each actor to
    join the group, driver-side."""
    if len(actors) != len(ranks):
        raise ValueError("actors and ranks must have equal length")
    refs = [a.ray_tpu_collective_init.remote(world_size, r, backend,
                                                group_name)
            for a, r in zip(actors, ranks)]
    from .. import get as ray_get

    ray_get(refs)


class CollectiveActorMixin:
    """Mix into actor classes used with create_collective_group (gives the
    driver a hook method to make the actor join the group)."""

    def ray_tpu_collective_init(self, world_size: int, rank: int,
                                    backend: str, group_name: str) -> bool:
        init_collective_group(world_size, rank, backend, group_name)
        return True


def destroy_collective_group(group_name: str = "default") -> None:
    with _groups_lock:
        g = _groups.pop(group_name, None)
    if g is not None and g.rank == 0:
        kv = _kv()
        for key in kv.call("kv_keys", f"{group_name}/".encode(), _NS,
                           timeout=None):
            kv.call("kv_del", key, _NS, timeout=None)


def is_group_initialized(group_name: str = "default") -> bool:
    with _groups_lock:
        return group_name in _groups


def get_rank(group_name: str = "default") -> int:
    return _group(group_name).rank


def get_collective_group_size(group_name: str = "default") -> int:
    return _group(group_name).world_size


def _group(group_name: str) -> _Group:
    with _groups_lock:
        g = _groups.get(group_name)
    if g is None:
        raise RuntimeError(f"collective group {group_name!r} not initialized "
                           "in this process")
    return g


def _gather_to_root(g: _Group, seq: int, value: Any, root: int = 0
                    ) -> Optional[List[Any]]:
    """Every rank contributes; root returns the rank-ordered list."""
    _put(f"{g.name}/{seq}/in/{g.rank}", value)
    if g.rank != root:
        return None
    vals = [_get_blocking(f"{g.name}/{seq}/in/{r}")
            for r in range(g.world_size)]
    for r in range(g.world_size):
        _del(f"{g.name}/{seq}/in/{r}")
    return vals


def _bcast_from_root(g: _Group, seq: int, value: Any, root: int = 0) -> Any:
    """Root publishes; everyone reads; root GCs after all acks."""
    if g.rank == root:
        _put(f"{g.name}/{seq}/out", value)
        out = value
    else:
        out = _get_blocking(f"{g.name}/{seq}/out")
    _put(f"{g.name}/{seq}/ack/{g.rank}", True)
    if g.rank == root:
        for r in range(g.world_size):
            _get_blocking(f"{g.name}/{seq}/ack/{r}")
        for r in range(g.world_size):
            _del(f"{g.name}/{seq}/ack/{r}")
        _del(f"{g.name}/{seq}/out")
    return out


def allreduce(tensor, group_name: str = "default",
              op: str = ReduceOp.SUM):
    """Reference collective.py:258. Returns the reduced array (the reference
    mutates in place; numpy inputs here are written in place too when
    possible)."""
    g = _group(group_name)
    seq = g.next_seq()
    vals = _gather_to_root(g, seq, np.asarray(tensor))
    reduced = _REDUCERS[op](vals) if vals is not None else None
    out = _bcast_from_root(g, seq, reduced)
    try:
        np.copyto(tensor, out)
    except (TypeError, ValueError):
        pass
    return out


def reduce(tensor, dst_rank: int = 0, group_name: str = "default",
           op: str = ReduceOp.SUM):
    """Reference collective.py:329 — result only meaningful on dst_rank."""
    g = _group(group_name)
    seq = g.next_seq()
    vals = _gather_to_root(g, seq, np.asarray(tensor), root=0)
    reduced = _REDUCERS[op](vals) if vals is not None else None
    # root 0 computes; ship to dst via the broadcast slot, all ranks sync.
    out = _bcast_from_root(g, seq, reduced)
    if g.rank == dst_rank:
        try:
            np.copyto(tensor, out)
        except (TypeError, ValueError):
            pass
        return out
    return tensor


def barrier(group_name: str = "default") -> None:
    """Reference collective.py:298."""
    g = _group(group_name)
    seq = g.next_seq()
    _gather_to_root(g, seq, True)
    _bcast_from_root(g, seq, True)


def broadcast(tensor, src_rank: int = 0, group_name: str = "default"):
    """Reference collective.py:373."""
    g = _group(group_name)
    seq = g.next_seq()
    if g.rank == src_rank:
        _put(f"{g.name}/{seq}/out", np.asarray(tensor))
        out = np.asarray(tensor)
    else:
        out = _get_blocking(f"{g.name}/{seq}/out")
        try:
            np.copyto(tensor, out)
        except (TypeError, ValueError):
            pass
    _put(f"{g.name}/{seq}/ack/{g.rank}", True)
    if g.rank == src_rank:
        for r in range(g.world_size):
            _get_blocking(f"{g.name}/{seq}/ack/{r}")
        for r in range(g.world_size):
            _del(f"{g.name}/{seq}/ack/{r}")
        _del(f"{g.name}/{seq}/out")
    return out


def allgather(tensor_list: Optional[list], tensor,
              group_name: str = "default") -> list:
    """Reference collective.py:423: gathers every rank's tensor to all
    ranks. Returns the rank-ordered list (also written into tensor_list)."""
    g = _group(group_name)
    seq = g.next_seq()
    vals = _gather_to_root(g, seq, np.asarray(tensor))
    out = _bcast_from_root(g, seq, vals)
    if tensor_list is not None:
        tensor_list[:] = out
    return out


def reducescatter(tensor, tensor_list: Optional[list] = None,
                  group_name: str = "default", op: str = ReduceOp.SUM):
    """Reference collective.py:472: reduce a list of world_size tensors and
    scatter one shard per rank. `tensor_list` is this rank's contribution
    (world_size chunks); the reduced chunk for this rank is returned (and
    copied into `tensor`)."""
    g = _group(group_name)
    if tensor_list is None:
        tensor_list = list(np.array_split(np.asarray(tensor), g.world_size))
    seq = g.next_seq()
    vals = _gather_to_root(g, seq, [np.asarray(t) for t in tensor_list])
    if vals is not None:
        reduced = [_REDUCERS[op]([v[i] for v in vals])
                   for i in range(g.world_size)]
    else:
        reduced = None
    chunks = _bcast_from_root(g, seq, reduced)
    out = chunks[g.rank]
    try:
        np.copyto(tensor, out)
    except (TypeError, ValueError):
        pass
    return out


def send(tensor, dst_rank: int, group_name: str = "default") -> None:
    """Reference collective.py:531 — point-to-point."""
    g = _group(group_name)
    key = (g.rank, dst_rank)
    seq = g.p2p_seq[key] = g.p2p_seq.get(key, 0) + 1
    _put(f"{g.name}/p2p/{g.rank}->{dst_rank}/{seq}", np.asarray(tensor))


def recv(tensor, src_rank: int, group_name: str = "default"):
    """Reference collective.py:594."""
    g = _group(group_name)
    key = (src_rank, g.rank)
    seq = g.p2p_seq[key] = g.p2p_seq.get(key, 0) + 1
    out = _get_blocking(f"{g.name}/p2p/{src_rank}->{g.rank}/{seq}")
    _del(f"{g.name}/p2p/{src_rank}->{g.rank}/{seq}")
    try:
        np.copyto(tensor, out)
    except (TypeError, ValueError):
        pass
    return out
