"""Ray-Client-style proxy: ``ray_tpu.init("ray://host:port")``.

Reference: python/ray/util/client (the ray:// gRPC proxy that hosts a
server-side driver per remote client, so clients need only ONE outbound
connection and no inbound reachability — laptops behind NAT, notebook
kernels, CI). The rebuild keeps that shape on the native RPC plane:

- ``ClientProxy`` runs next to the head. Each client session gets its
  pins table (object refs + actor handles held alive server-side); the
  proxy executes submissions on its own driver Worker and returns
  opaque ids. Sessions idle past a timeout are reaped, dropping their
  pins so the distributed refcount can collect.
- ``ClientWorker`` is the client-side ``global_worker`` stand-in: the
  whole public API (put/get/wait/remote tasks/actors/cancel/kill and
  the conductor passthrough) routes through it unchanged — blocking
  calls block in the proxy, so the client polls nothing.

Scope matches the reference's client mode: the core API, not the
data-plane extras (Serve handles/compiled DAGs talk worker-to-worker
and need cluster-side execution). Pickled payloads mean the proxy
trusts its clients exactly as much as the reference's does.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from . import exceptions as exc
from ._private import serialization
from ._private.rpc import RemoteError, RpcClient, RpcServer

_MARKER = "__ray_tpu_client_ref__"
SESSION_IDLE_TIMEOUT_S = 600.0


# --------------------------------------------------------------- server


class _Session:
    def __init__(self):
        self.refs: Dict[str, Any] = {}        # id -> ObjectRef (pins)
        self.last_active = time.monotonic()


class ClientProxyHandler:
    """RPC surface of the proxy. Every method takes the session id
    first; unknown sessions are (re)created on the fly so a proxy
    restart degrades to lost pins, not broken clients."""

    def __init__(self, worker):
        self.w = worker                        # server-side driver Worker
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()

    # -- session plumbing --------------------------------------------------
    def _session(self, sid: str) -> _Session:
        with self._lock:
            s = self._sessions.get(sid)
            if s is None:
                s = self._sessions[sid] = _Session()
            s.last_active = time.monotonic()
            return s

    def reap_idle(self, timeout_s: float = SESSION_IDLE_TIMEOUT_S) -> int:
        now = time.monotonic()
        with self._lock:
            dead = [sid for sid, s in self._sessions.items()
                    if now - s.last_active > timeout_s]
            for sid in dead:
                del self._sessions[sid]
        return len(dead)

    def client_connect(self, sid: str) -> Dict[str, Any]:
        self._session(sid)
        return {"conductor": list(self.w.conductor_address)}

    def client_disconnect(self, sid: str) -> None:
        with self._lock:
            self._sessions.pop(sid, None)

    # -- ref marshalling ---------------------------------------------------
    def _pin(self, s: _Session, ref) -> Dict[str, Any]:
        s.refs[ref.id] = ref
        return {_MARKER: ref.id}

    def _unpin_swap(self, s: _Session, x: Any) -> Any:
        """Client arg structures carry {_MARKER: id} where they held a
        ClientObjectRef; swap back to the pinned real ref."""
        if isinstance(x, dict):
            if set(x.keys()) == {_MARKER}:
                ref = s.refs.get(x[_MARKER])
                if ref is None:
                    raise exc.ObjectLostError(
                        x[_MARKER], "client ref unknown to this session "
                        "(proxy restarted?)")
                return ref
            return {k: self._unpin_swap(s, v) for k, v in x.items()}
        if isinstance(x, list):
            return [self._unpin_swap(s, v) for v in x]
        if isinstance(x, tuple):
            return tuple(self._unpin_swap(s, v) for v in x)
        return x

    # -- data plane --------------------------------------------------------
    def client_put(self, sid: str, value: Any) -> Dict[str, Any]:
        s = self._session(sid)
        return self._pin(s, self.w.put(value))

    def client_get(self, sid: str, ids: List[str],
                   timeout: Optional[float]) -> List[Any]:
        s = self._session(sid)
        refs = []
        for oid in ids:
            ref = s.refs.get(oid)
            if ref is None:
                raise exc.ObjectLostError(oid, "unknown client ref")
            refs.append(ref)
        return self.w.get(refs, timeout=timeout)

    def client_wait(self, sid: str, ids: List[str], num_returns: int,
                    timeout: Optional[float]) -> Tuple[List[str], List[str]]:
        s = self._session(sid)
        refs = [s.refs[oid] for oid in ids]
        ready, not_ready = self.w.wait(refs, num_returns=num_returns,
                                       timeout=timeout)
        return [r.id for r in ready], [r.id for r in not_ready]

    def client_release(self, sid: str, ids: List[str]) -> None:
        s = self._session(sid)
        for oid in ids:
            s.refs.pop(oid, None)

    # -- submission --------------------------------------------------------
    def client_task(self, sid: str, fn_bytes: bytes, args, kwargs,
                    options: Dict[str, Any]):
        s = self._session(sid)
        fn = serialization.loads(fn_bytes)
        args = self._unpin_swap(s, tuple(args))
        kwargs = self._unpin_swap(s, dict(kwargs))
        out = self.w.submit_task(fn, args, kwargs, **options)
        refs = out if isinstance(out, list) else [out]
        wired = [self._pin(s, r) for r in refs]
        return wired if isinstance(out, list) else wired[0]

    def client_create_actor(self, sid: str, cls_bytes: bytes, args, kwargs,
                            options: Dict[str, Any]) -> Dict[str, Any]:
        s = self._session(sid)
        cls = serialization.loads(cls_bytes)
        args = self._unpin_swap(s, tuple(args))
        kwargs = self._unpin_swap(s, dict(kwargs))
        return self.w.create_actor(cls, args, kwargs, options)

    def client_actor_task(self, sid: str, actor_id: str, address, method,
                          args, kwargs, num_returns: int, seqno: int,
                          caller_id: str, max_task_retries: int):
        s = self._session(sid)
        args = self._unpin_swap(s, tuple(args))
        kwargs = self._unpin_swap(s, dict(kwargs))
        out = self.w.submit_actor_task(
            actor_id, tuple(address), method, args, kwargs, num_returns,
            seqno, caller_id, max_task_retries=max_task_retries)
        refs = out if isinstance(out, list) else [out]
        wired = [self._pin(s, r) for r in refs]
        return wired if isinstance(out, list) else wired[0]

    def client_cancel(self, sid: str, oid: str, force: bool) -> None:
        s = self._session(sid)
        ref = s.refs.get(oid)
        if ref is not None:
            self.w.cancel(ref, force=force)

    # -- control-plane passthrough ----------------------------------------
    def client_conductor(self, sid: str, method: str, args, kwargs):
        self._session(sid)
        return self.w.conductor.call(method, *args, timeout=60.0, **kwargs)


class ClientProxy:
    """Hosts a ClientProxyHandler on its own RpcServer next to the
    head (reference: the ray client server the head starts on :10001)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        from ._private.worker import global_worker

        if global_worker is None:
            raise RuntimeError("start the proxy inside an initialized "
                               "cluster (ray_tpu.init first)")
        self.handler = ClientProxyHandler(global_worker)
        self.server = RpcServer(self.handler, host=host, port=port,
                                max_workers=64).start()
        self._stopped = threading.Event()
        threading.Thread(target=self._reap_loop, daemon=True,
                         name="client-proxy-reap").start()

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def _reap_loop(self) -> None:
        while not self._stopped.wait(30.0):
            try:
                self.handler.reap_idle()
            except Exception:  # noqa: BLE001
                pass

    def stop(self) -> None:
        self._stopped.set()
        self.server.stop()


# --------------------------------------------------------------- client


class ClientObjectRef:
    """Opaque handle to an object pinned in the proxy session."""

    __slots__ = ("id", "_client")

    def __init__(self, id: str, client: "ClientWorker"):
        self.id = id
        self._client = client

    def __repr__(self):
        return f"ClientObjectRef({self.id[:12]}…)"

    def __del__(self):
        c = self._client
        if c is not None and not c._closed:
            c._release_later(self.id)


def _wire_ref(x: Any) -> bool:
    return isinstance(x, dict) and set(x.keys()) == {_MARKER}


class _ConductorShim:
    def __init__(self, client: "ClientWorker"):
        self._c = client

    def call(self, method: str, *args, timeout: Optional[float] = None,
             **kwargs):
        return self._c._call("client_conductor", method, list(args), kwargs)

    def notify(self, method: str, *args, **kwargs) -> None:
        self.call(method, *args, **kwargs)


class ClientWorker:
    """global_worker stand-in for ray:// mode — same duck-typed surface
    the public API uses, every operation forwarded to the proxy."""

    mode = "client"

    def __init__(self, address: Tuple[str, int]):
        self._rpc = RpcClient(tuple(address), connect_retries=5)
        self.session_id = uuid.uuid4().hex
        self._closed = False
        self._pending_release: List[str] = []
        self._release_lock = threading.Lock()
        self._last_release_flush = time.monotonic()
        info = self._call("client_connect")
        self.conductor_address = tuple(info["conductor"])
        self.conductor = _ConductorShim(self)

    # -- plumbing ----------------------------------------------------------
    def _call(self, method: str, *args):
        # Piggyback pending releases on session traffic every few seconds
        # regardless of batch size.
        if (self._pending_release
                and time.monotonic() - self._last_release_flush > 3.0):
            self._flush_releases()
        try:
            return self._rpc.call(method, self.session_id, *args,
                                  timeout=None)
        except RemoteError as e:
            raise e.cause if isinstance(e.cause, exc.RayTpuError) else e \
                from None

    def _release_later(self, oid: str) -> None:
        with self._release_lock:
            self._pending_release.append(oid)
        # Size-triggered flush; _flush_releases also runs time-based from
        # _call so a slow-dropping session cannot pin objects server-side
        # behind the 100-entry batch threshold indefinitely.
        self._flush_releases(min_batch=100)

    def _flush_releases(self, min_batch: int = 1) -> None:
        with self._release_lock:
            if len(self._pending_release) < min_batch:
                return
            batch, self._pending_release = self._pending_release, []
            self._last_release_flush = time.monotonic()
        try:
            self._rpc.notify("client_release", self.session_id, batch)
        except Exception:  # noqa: BLE001 — reaper will collect
            pass

    def _swap_out(self, x: Any) -> Any:
        if isinstance(x, ClientObjectRef):
            return {_MARKER: x.id}
        if isinstance(x, list):
            return [self._swap_out(v) for v in x]
        if isinstance(x, tuple):
            return tuple(self._swap_out(v) for v in x)
        if isinstance(x, dict):
            return {k: self._swap_out(v) for k, v in x.items()}
        return x

    def _wrap(self, wired):
        if isinstance(wired, list):
            return [self._wrap(w) for w in wired]
        return ClientObjectRef(wired[_MARKER], self)

    # -- public surface (mirrors Worker) ----------------------------------
    def put(self, value: Any) -> ClientObjectRef:
        return self._wrap(self._call("client_put", value))

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        ref_list = [refs] if single else list(refs)
        for r in ref_list:
            if not isinstance(r, ClientObjectRef):
                raise TypeError(f"get() expects ClientObjectRef, got "
                                f"{type(r)}")
        out = self._call("client_get", [r.id for r in ref_list], timeout)
        return out[0] if single else out

    def wait(self, refs, num_returns: int = 1,
             timeout: Optional[float] = None, fetch_local: bool = True):
        by_id = {r.id: r for r in refs}
        ready, not_ready = self._call(
            "client_wait", [r.id for r in refs], num_returns, timeout)
        return [by_id[i] for i in ready], [by_id[i] for i in not_ready]

    def submit_task(self, fn, args, kwargs, **options):
        wired = self._call(
            "client_task", serialization.dumps(fn),
            self._swap_out(tuple(args)), self._swap_out(dict(kwargs)),
            options)
        return self._wrap(wired)

    def create_actor(self, cls, args, kwargs, options: Dict[str, Any]):
        return self._call(
            "client_create_actor", serialization.dumps(cls),
            self._swap_out(tuple(args)), self._swap_out(dict(kwargs)),
            dict(options))

    def submit_actor_task(self, actor_id, address, method, args, kwargs,
                          num_returns, seqno, caller_id,
                          max_task_retries: int = 0):
        wired = self._call(
            "client_actor_task", actor_id, list(address or ()), method,
            self._swap_out(tuple(args)), self._swap_out(dict(kwargs)),
            num_returns, seqno, caller_id, max_task_retries)
        return self._wrap(wired)

    def cancel(self, ref, force: bool = False) -> None:
        self._call("client_cancel", ref.id, bool(force))

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._flush_releases()
        try:
            self._rpc.call("client_disconnect", self.session_id,
                           timeout=5.0)
        except Exception:  # noqa: BLE001 — proxy may be gone
            pass
        self._rpc.close()


def connect(address: str) -> ClientWorker:
    """Connect to a ClientProxy; `address` is 'host:port' (the ray://
    prefix is stripped by ray_tpu.init)."""
    host, port = address.rsplit(":", 1)
    return ClientWorker((host, int(port)))
