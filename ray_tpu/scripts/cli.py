"""CLI — analog of the reference's python/ray/scripts/scripts.py
(`ray start` :568, `stop` :1044, `submit` :1578, plus status/memory/
timeline/logs) and util/state/state_cli.py (`ray list ...`).

Run as ``python -m ray_tpu <command>``."""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time
from typing import Optional

_ADDR_FILE = os.path.join(tempfile.gettempdir(), "ray_tpu",
                          "head_address.txt")


def _resolve_address(explicit: Optional[str]) -> str:
    if explicit:
        return explicit
    env = os.environ.get("RAY_TPU_ADDRESS")
    if env:
        return env
    try:
        with open(_ADDR_FILE) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise SystemExit(
            "no cluster address: pass --address, set RAY_TPU_ADDRESS, or "
            "start a head on this machine with "
            "`python -m ray_tpu start --head`")


def _connect(args) -> None:
    import ray_tpu

    ray_tpu.init(address=_resolve_address(getattr(args, "address", None)),
                 ignore_reinit_error=True)


def cmd_start(args) -> None:
    """Foreground head or worker-host process — reference `ray start`
    (scripts.py:568: --head starts GCS+raylet; --address joins an
    existing cluster as a worker node via the per-host NodeAgent)."""
    if not args.head:
        if not getattr(args, "address", None):
            raise SystemExit("pass --head to start a cluster or "
                             "--address host:port to join one")
        from ray_tpu._private.node_agent import main as agent_main

        argv = ["--address", args.address, "--num-cpus",
                str(args.num_cpus)]
        if args.resources:
            argv += ["--resources", args.resources]
        if getattr(args, "node_id", None):
            argv += ["--node-id", args.node_id]
        agent_main(argv)
        return
    from ray_tpu._private.conductor import Conductor

    resources = {"CPU": float(args.num_cpus)}
    if args.resources:
        resources.update(json.loads(args.resources))
    session_dir = os.path.join(
        tempfile.gettempdir(), "ray_tpu",
        f"session_{time.strftime('%Y%m%d-%H%M%S')}_{os.getpid()}")
    os.makedirs(session_dir, exist_ok=True)
    c = Conductor(resources, session_dir, host=args.host,
                  port=args.port).start()
    host, port = c.address
    dash = None
    if not args.no_dashboard:
        try:
            from ray_tpu.dashboard import DashboardServer

            dash = DashboardServer((host, port), host=args.host,
                                   port=args.dashboard_port).start()
            print(f"dashboard at {dash.url}", flush=True)
        except Exception as e:  # noqa: BLE001 — aiohttp/port problems
            print(f"dashboard not started: {e}", flush=True)
    os.makedirs(os.path.dirname(_ADDR_FILE), exist_ok=True)
    with open(_ADDR_FILE, "w") as f:
        f.write(f"{host}:{port}")
    print(f"ray_tpu head started at {host}:{port}\n"
          f"  session dir: {session_dir}\n"
          f"  connect with ray_tpu.init(address=\"{host}:{port}\") "
          f"or RAY_TPU_ADDRESS={host}:{port}", flush=True)
    # The head lives in this process either way (use `&`/systemd to
    # background it); --block is accepted for reference-CLI compatibility.
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if dash is not None:
            dash.stop()
        c.stop()


def cmd_stop(args) -> None:
    from ray_tpu._private.rpc import RpcClient

    addr = _resolve_address(args.address)
    host, _, port = addr.rpartition(":")
    try:
        RpcClient((host, int(port))).call("shutdown_cluster", timeout=10.0)
        print(f"head at {addr} stopped")
    except Exception as e:  # noqa: BLE001
        # keep the address file: the head may still be alive and reachable
        raise SystemExit(f"could not reach head at {addr}: {e}")
    try:
        os.unlink(_ADDR_FILE)
    except OSError:
        pass
    import glob

    from ray_tpu._private.object_store import cleanup_leaked_segments

    # The head tears down asynchronously (SIGTERM grace then SIGKILL can
    # take >3s): poll-sweep until the segments' owners are gone.
    removed, deadline = 0, time.monotonic() + 6.0
    while True:
        removed += cleanup_leaked_segments()
        if not glob.glob("/dev/shm/rtpu_a_*") \
                or time.monotonic() >= deadline:
            break
        time.sleep(0.5)
    if removed:
        print(f"removed {removed} leaked shm segment(s)")


def cmd_status(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps(state.cluster_summary(), indent=2, default=str))


def cmd_list(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    fns = {"nodes": state.list_nodes, "workers": state.list_workers,
           "actors": state.list_actors, "tasks": state.list_tasks,
           "objects": state.list_objects,
           "placement-groups": state.list_placement_groups}
    print(json.dumps(fns[args.kind](), indent=2, default=str))


def cmd_summary(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps(state.summarize_tasks(), indent=2, default=str))


def cmd_memory(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    print(json.dumps(state.list_objects(), indent=2, default=str))


def cmd_timeline(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    n = len(state.timeline(args.output, merged=args.merged))
    what = "merged (tasks+spans+train steps)" if args.merged else "task"
    print(f"wrote {n} {what} events to {args.output} "
          f"(load in chrome://tracing or Perfetto)")


def cmd_train_status(args) -> None:
    """Flight-recorder view of running/recent training gangs: per-rank
    step stats, the latest step's time breakdown, skew, stragglers."""
    _connect(args)
    from ray_tpu.util import state

    progress = state.train_progress(getattr(args, "run", None))
    if args.json:
        print(json.dumps(progress, indent=2, default=str))
        return
    if not progress:
        print("no training telemetry recorded "
              "(is the gang using ray_tpu.train.report()?)")
        return
    for run_id, run in progress.items():
        print(f"run {run_id}: world={run['world']} "
              f"last_step={run['last_step']} "
              f"steps_buffered={run['steps_buffered']}")
        bd = run.get("last_step_breakdown") or {}
        if bd:
            parts = " ".join(f"{k[:-3]}={v:.1f}ms" for k, v in bd.items())
            print(f"  last step: {parts}")
        skew = run.get("last_step_skew") or {}
        if skew:
            print(f"  skew: min={skew['min_ms']:.1f}ms "
                  f"median={skew['median_ms']:.1f}ms "
                  f"p99={skew['p99_ms']:.1f}ms "
                  f"max/median={skew['max_over_median']:.2f}")
        for rank, st in sorted(run["per_rank"].items()):
            extra = ""
            if st.get("tokens_per_sec"):
                extra += f" tok/s={st['tokens_per_sec']:.0f}"
            if st.get("mfu") is not None:
                extra += f" mfu={100 * st['mfu']:.2f}%"
            mark = " <- STRAGGLER" if rank in run["stragglers"] else ""
            print(f"  rank {rank}: steps={st['steps']} "
                  f"mean={st['mean_ms']:.1f}ms p99={st['p99_ms']:.1f}ms"
                  f"{extra}{mark}")


def _print_event_tail(events, n: int) -> None:
    """Shared `[HH:MM:SS] kind k=v ...` tail rendering for the event
    logs (resilience / kvcache / pipeline)."""
    for ev in events[-n:]:
        when = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
        extra = {k: v for k, v in ev.items()
                 if k not in ("kind", "ts") and v is not None}
        print(f"  [{when}] {ev.get('kind')} "
              + " ".join(f"{k}={v}" for k, v in extra.items()))


def cmd_resilience_status(args) -> None:
    """Recovery-subsystem view: quarantined/draining hosts with their
    decayed failure scores, event counters, and recent events."""
    _connect(args)
    from ray_tpu.util import state

    st = state.resilience_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    excluded = set(st.get("excluded") or [])
    print(f"quarantine threshold: {st['threshold']:g} "
          f"(half-life {st['half_life_s']:g}s)")
    domains = st.get("domains") or {}
    if not domains:
        print("no failure history recorded")
    for node_id, d in sorted(domains.items()):
        flags = []
        if d.get("quarantined"):
            flags.append("QUARANTINED" + (" (manual)" if d.get("manual")
                                          else ""))
        if d.get("draining"):
            flags.append(f"DRAINING {d['drain_remaining_s']:.0f}s left "
                         f"({d.get('drain_reason')})")
        if d.get("exempt"):
            flags.append("exempt")
        mark = " <- EXCLUDED" if node_id in excluded else ""
        print(f"  {node_id[:16]}: score={d['score']:.2f} "
              f"failures={d['failures']}"
              + (f" last={d['last_kind']}" if d.get("last_kind") else "")
              + (f" [{', '.join(flags)}]" if flags else "") + mark)
    counters = st.get("counters") or {}
    if counters:
        print("counters: " + " ".join(f"{k}={v}" for k, v
                                      in sorted(counters.items())))
    if st.get("last_ttr_s") is not None:
        print(f"last time-to-recovery: {st['last_ttr_s']:.2f}s")
    _print_event_tail(st.get("recent_events") or [], args.events)


def cmd_weights(args) -> None:
    """`ray_tpu weights list|inspect|gc` — the live weight fabric's
    registry view (ray_tpu.weights): committed versions per name with
    sizes and host counts, one version's full manifest (minus chunk
    payloads), or an operator keep-last-K GC."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    w = worker_mod.global_worker
    if args.weights_cmd == "list":
        listing = state.weight_versions(getattr(args, "name", None))
        if args.json:
            print(json.dumps(listing, indent=2, default=str))
            return
        names = listing.get("names") or {}
        if not names and not listing.get("pending"):
            print("no weight versions published")
        for name, rec in sorted(names.items()):
            print(f"{name}: latest=v{rec['latest']} "
                  f"({len(rec['versions'])} kept)")
            for v in rec["versions"]:
                when = time.strftime("%H:%M:%S",
                                     time.localtime(v.get("ts", 0)))
                print(f"  v{v['version']}: step={v.get('step')} "
                      f"bytes={v['total_bytes']} hosts={v['num_hosts']} "
                      f"leaves={v['n_leaves']} chunks={v['n_chunks']} "
                      f"[{when}]"
                      + (f" run={v['run_id']}" if v.get("run_id") else ""))
        for p in listing.get("pending") or []:
            print(f"  PENDING {p['name']} v{p['version']}: "
                  f"{len(p['hosts_committed'])}/{p['num_hosts']} hosts, "
                  f"age {p['age_s']:.1f}s")
    elif args.weights_cmd == "inspect":
        m = w.conductor.call("weights_get_manifest", args.name,
                             args.version, timeout=10.0)
        if m is None:
            raise SystemExit(
                f"no committed version "
                f"{'(latest)' if args.version is None else args.version} "
                f"of {args.name!r}")
        m = dict(m)
        m.pop("treedef", None)  # pickled bytes, not printable
        print(json.dumps(m, indent=2, default=str))
    elif args.weights_cmd == "gc":
        dropped = w.conductor.call("weights_gc", args.name, args.keep,
                                   timeout=10.0)
        print(f"dropped {dropped} version(s) of {args.name!r}")


def cmd_kvcache(args) -> None:
    """`ray_tpu kvcache` — paged-KV prefix-cache view (models/kvcache):
    per-engine hit/miss/eviction counters and pool utilization plus the
    cluster totals every other surface (state API, /api/kvcache,
    Prometheus, timeline markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.kv_cache_stats(getattr(args, "engine", None))
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    engines = st.get("engines") or {}
    totals = st.get("totals") or {}
    if not engines:
        print("no kv-cache telemetry recorded (is a "
              "ContinuousBatchingEngine with the prefix cache enabled "
              "running?)")
        return
    print(f"totals: lookups={totals.get('lookups', 0)} "
          f"hit_rate={totals.get('hit_rate', 0.0):.2%} "
          f"token_reuse={totals.get('token_reuse_rate', 0.0):.2%} "
          f"evictions={totals.get('evictions', 0)} "
          f"cow={totals.get('cow_copies', 0)}")
    for key, s in sorted(engines.items()):
        if not s.get("enabled", False):
            # decode replicas under disaggregation run cache-disabled:
            # they adopt prefilled KV, they never prefill
            print(f"  {key}: prefix cache DISABLED "
                  f"(admitted={s.get('admitted', 0)} "
                  f"prefill={s.get('prefill_admitted', 0)} "
                  f"adopted={s.get('adopted', 0)})")
            continue
        print(f"  {key}: hits={s.get('hits', 0)} "
              f"partial={s.get('partial_hits', 0)} "
              f"misses={s.get('misses', 0)} "
              f"reused_tok={s.get('reused_tokens', 0)} "
              f"prefilled_tok={s.get('prefilled_tokens', 0)} "
              f"pool={s.get('pool_utilization', 0.0):.0%} "
              f"({s.get('cached_blocks', 0)} cached / "
              f"{s.get('pinned_blocks', 0)} pinned / "
              f"{s.get('num_blocks', 0)} blocks) "
              f"evictions={s.get('evictions', 0)} "
              f"cow={s.get('cow_copies', 0)} "
              f"invalidations={s.get('invalidations', 0)}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_kvcache_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_speculate(args) -> None:
    """`ray_tpu speculate` — speculative-decoding view (models/engine):
    per-engine draft proposal/acceptance counters, tokens-per-verify
    and acceptance rate plus the cluster totals every other surface
    (state API, /api/speculation, Prometheus, the kvcache timeline
    lane's spec markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.speculation_stats(getattr(args, "engine", None))
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    engines = st.get("engines") or {}
    totals = st.get("totals") or {}
    if not engines:
        print("no speculation telemetry recorded (is an engine running "
              "with speculate_k > 0 / RAY_TPU_SPECULATE_K set?)")
        return
    print(f"totals: proposed={totals.get('spec_proposed', 0)} "
          f"accepted={totals.get('spec_accepted', 0)} "
          f"acceptance={totals.get('acceptance_rate', 0.0):.2%} "
          f"verify_ticks={totals.get('spec_verify_ticks', 0)} "
          f"tokens/verify={totals.get('tokens_per_verify', 0.0):.2f}")
    for key, s in sorted(engines.items()):
        print(f"  {key}: k={s.get('speculate_k', 0)} "
              f"proposed={s.get('spec_proposed', 0)} "
              f"accepted={s.get('spec_accepted', 0)} "
              f"acceptance={s.get('acceptance_rate', 0.0):.2%} "
              f"tokens/verify={s.get('tokens_per_verify', 0.0):.2f} "
              f"int8_kv={'on' if s.get('kv_int8') else 'off'}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_kvcache_events", 10_000,
                                  timeout=10.0)
        spec = [e for e in events
                if str(e.get("kind", "")).startswith("spec_")]
        _print_event_tail(spec[-args.events:], args.events)


def cmd_pipeline(args) -> None:
    """`ray_tpu pipeline` — MPMD pipeline view (ray_tpu.mpmd): per-
    pipeline stage registry + per-stage run stats (bubble fraction,
    channel bytes) plus the cluster totals every other surface (state
    API, /api/pipeline, Prometheus, timeline markers) reports from the
    same registry."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.pipeline_status(getattr(args, "name", None))
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    pipelines = st.get("pipelines") or {}
    if not pipelines:
        print("no MPMD pipelines registered (is a PipelineConductor/"
              "PipelineTrainer running?)")
        return
    for name, rec in sorted(pipelines.items()):
        status = "closed" if rec.get("closed") else (
            "formed" if rec.get("formed") else
            f"forming {len(rec.get('stages') or {})}/"
            f"{rec['num_stages']}")
        est = rec.get("bubble_estimate")
        print(f"{name}: stages={rec['num_stages']} "
              f"schedule={rec.get('schedule')} "
              f"microbatches={rec.get('num_microbatches')} [{status}]"
              + (f" est_bubble={est:.1%}" if est is not None else ""))
        totals = rec.get("totals") or {}
        if totals.get("steps"):
            mean = totals.get("bubble_fraction_mean")
            print(f"  totals: steps={totals['steps']} "
                  f"activation_bytes={totals['activation_bytes']}"
                  + (f" bubble_mean={mean:.1%}"
                     if mean is not None else ""))
        stages = rec.get("stages") or {}
        stats = rec.get("stats") or {}
        for s in sorted(stages, key=int):
            reg = stages[s]
            st_s = stats.get(s) or stats.get(str(s)) or {}
            line = (f"  stage {s}: slice={reg.get('slice_id')} "
                    f"worker={str(reg.get('worker_id'))[:12]}")
            if st_s:
                line += (f" steps={st_s.get('steps')} "
                         f"bubble={st_s.get('bubble_fraction', 0.0):.1%}"
                         f" sent={st_s.get('sent_bytes', 0)}B "
                         f"recv={st_s.get('recv_bytes', 0)}B")
            print(line)
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_pipeline_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_online(args) -> None:
    """`ray_tpu online` — online learning loop view (ray_tpu.online):
    per-sampler rollout/staleness stats, buffer occupancy and
    backpressure, learner ingest progress, plus the cluster totals
    every other surface (state API, /api/online, Prometheus, timeline
    markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.online_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    totals = st.get("totals") or {}
    if not (st.get("samplers") or st.get("buffers")
            or st.get("learners")):
        print("no online-loop telemetry recorded (is an "
              "OnlineTrainer / RolloutSampler running?)")
        return
    stale = totals.get("max_staleness_versions")
    print(f"totals: samplers={totals.get('samplers', 0)} "
          f"rollouts={totals.get('rollouts', 0)} "
          f"rollout_tokens={totals.get('rollout_tokens', 0)} "
          f"ingested={totals.get('ingested_rollouts', 0)} "
          f"buffer={totals.get('buffer_occupancy', 0)}"
          f"/{totals.get('buffer_capacity', 0)} "
          f"max_staleness={stale if stale is not None else '-'}")
    for key, s in sorted((st.get("samplers") or {}).items()):
        print(f"  {key}: rollouts={s.get('rollouts', 0)} "
              f"tokens={s.get('rollout_tokens', 0)} "
              f"serving=v{s.get('serving_version')} "
              f"latest=v{s.get('latest_version')} "
              f"staleness={s.get('staleness_versions')} "
              f"(max {s.get('max_staleness_versions')}) "
              f"swaps={s.get('swap_count', 0)}"
              + ("" if s.get("registry_reachable", True)
                 else " [REGISTRY UNREACHABLE]"))
    for key, b in sorted((st.get("buffers") or {}).items()):
        print(f"  {key}: occupancy={b.get('occupancy', 0)}"
              f"/{b.get('capacity', 0)} in={b.get('total_in', 0)} "
              f"out={b.get('total_out', 0)} "
              f"rejected={b.get('rejected', 0)}")
    for key, l in sorted((st.get("learners") or {}).items()):
        print(f"  {key}: steps={l.get('steps', 0)} "
              f"ingested={l.get('ingested_rollouts', 0)} "
              f"last_loss={l.get('last_loss')} "
              f"published=v{l.get('published_version')}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_online_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_disagg(args) -> None:
    """`ray_tpu disagg` — disaggregated prefill/decode serving view
    (serve/disagg.py): prefill-tier reuse + published KV, decode-tier
    transfer accounting (shm vs rpc — the no-full-copy evidence),
    router dispatch/shed/queue-depth, plus the cluster totals every
    other surface (state API, /api/disagg, Prometheus, timeline
    markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.disagg_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    totals = st.get("totals") or {}
    if not (st.get("prefill") or st.get("decode") or st.get("routers")):
        print("no disagg telemetry recorded (is a PrefillServer/"
              "DecodeServer/DisaggRouter running?)")
        return
    print(f"totals: transfers={totals.get('transfers', 0)} "
          f"kv_bytes={totals.get('kv_fetched_bytes', 0)} "
          f"(shm={totals.get('shm_bytes', 0)} "
          f"rpc={totals.get('rpc_bytes', 0)}) "
          f"adopted={totals.get('adopted', 0)} "
          f"dispatched={totals.get('dispatched', 0)} "
          f"shed={totals.get('shed', 0)} "
          f"queue_depth={totals.get('queue_depth', 0)} "
          f"(max {totals.get('max_queue_depth_seen', 0)})")
    for key, p in sorted((st.get("prefill") or {}).items()):
        pc = p.get("prefix_cache") or {}
        print(f"  {key}: prefills={p.get('prefills', 0)} "
              f"prefilled_tok={p.get('prefilled_tokens', 0)} "
              f"reused_tok={p.get('reused_tokens', 0)} "
              f"published={p.get('published_transfers', 0)} "
              f"({p.get('published_bytes', 0)}B) "
              f"held={p.get('held_transfers', 0)} "
              f"acked={p.get('acked', 0)}"
              + (f" hit_rate={pc.get('hit_rate', 0.0):.2%}"
                 if pc else ""))
    for key, d in sorted((st.get("decode") or {}).items()):
        print(f"  {key}: transfers={d.get('transfers', 0)} "
              f"fetched={d.get('kv_fetched_bytes', 0)}B "
              f"(shm={d.get('shm_bytes', 0)} rpc={d.get('rpc_bytes', 0)}) "
              f"adopted={d.get('adopted', 0)} "
              f"slots={d.get('free_slots', 0)}/{d.get('capacity', 0)} "
              f"prefill_programs={d.get('prefill_programs', 0)}")
    for key, r in sorted((st.get("routers") or {}).items()):
        print(f"  {key}: mode={r.get('mode')} "
              f"dispatched={r.get('dispatched', 0)} "
              f"completed={r.get('completed', 0)} "
              f"shed={r.get('shed', 0)} "
              f"pending={r.get('pending', 0)} "
              f"(max {r.get('max_pending', 0)}, "
              f"depth_knob={r.get('max_queue_depth')})")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_disagg_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_kvplane(args) -> None:
    """`ray_tpu kvplane` — global KV plane view (serve/kvplane.py):
    per-replica host arenas (tier-2 entries/bytes, spills absorbed,
    re-adopted tokens), tier-3 publish/adopt traffic through the chunk
    fabric, router directory routing outcomes (hit/fallback/miss), the
    conductor's prefix-directory summary, plus the cluster totals every
    other surface (state API, /api/kvplane, Prometheus, timeline
    markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.kvplane_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    comps = st.get("components") or {}
    if not comps:
        print("no kvplane telemetry recorded (is a kvplane-enabled "
              "PrefillServer/DisaggRouter running?)")
        return
    t = st.get("totals") or {}
    print(f"totals: spills={t.get('spills', 0)} "
          f"({t.get('spill_bytes', 0)}B) "
          f"tier2_hits={t.get('tier2_hits', 0)}"
          f"/{t.get('tier2_probes', 0)} "
          f"({t.get('tier2_hit_rate', 0.0):.2%}) "
          f"t2_reused_tok={t.get('tier2_reused_tokens', 0)} "
          f"t3_publishes={t.get('tier3_publishes', 0)} "
          f"t3_adopts={t.get('tier3_adopts', 0)} "
          f"t3_reused_tok={t.get('tier3_reused_tokens', 0)} "
          f"directory_hit_rate={t.get('directory_hit_rate', 0.0):.2%} "
          f"arena={t.get('arena_entries', 0)} entries "
          f"({t.get('arena_bytes', 0)}B)")
    d = st.get("directory") or {}
    ns = d.get("namespaces") or {}
    ctr = d.get("counters") or {}
    print(f"directory: entries={d.get('entries', 0)} "
          f"({d.get('nbytes', 0)}B) namespaces={len(ns)} "
          f"publishes={ctr.get('publishes', 0)} "
          f"lookups={ctr.get('lookups', 0)} "
          f"reaped={ctr.get('reaped', 0)} "
          f"gced={ctr.get('gced', 0)} "
          f"unpublished={ctr.get('unpublished', 0)}")
    for key, c in sorted(comps.items()):
        if c.get("role") == "router":
            print(f"  {key}: directory hits={c.get('directory_hits', 0)} "
                  f"fallbacks={c.get('directory_fallbacks', 0)} "
                  f"misses={c.get('directory_misses', 0)}"
                  + (f" hit_rate={c['directory_hit_rate']:.2%}"
                     if c.get("directory_hit_rate") is not None else ""))
        else:
            print(f"  {key}: arena={c.get('entries', 0)} entries "
                  f"({c.get('bytes', 0)}B/{c.get('max_bytes', 0)}B) "
                  f"spills={c.get('spills', 0)} "
                  f"t2_hits={c.get('tier2_hits', 0)} "
                  f"t2_reused_tok={c.get('tier2_reused_tokens', 0)} "
                  f"t3_pub={c.get('tier3_publishes', 0)} "
                  f"t3_adopt={c.get('tier3_adopts', 0)} "
                  f"storms={c.get('evict_storms', 0)}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_kvplane_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_servefault(args) -> None:
    """`ray_tpu servefault` — serving-plane fault-tolerance view
    (serve/disagg.py failover + serve/autoscale.py self-healing):
    per-router failovers by phase and sheds by attributed cause,
    per-healer deaths/replacements/breaker state, plus the cluster
    totals every other surface (state API, /api/servefault,
    Prometheus, resilience-lane timeline markers) reports from the
    same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.servefault_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    if not (st.get("routers") or st.get("healers")):
        print("no servefault telemetry recorded (is a DisaggRouter/"
              "DisaggAutoscaler running?)")
        return
    totals = st.get("totals") or {}
    fo = totals.get("failovers") or {}
    sheds = totals.get("sheds_by_cause") or {}
    repl = totals.get("replacements") or {}
    shed_txt = " ".join(f"{k}:{v}"
                        for k, v in sorted(sheds.items())) or "none"
    print(f"totals: failovers=prefill:{fo.get('prefill', 0)}"
          f"/decode:{fo.get('decode', 0)} "
          f"failed_over_requests={totals.get('failover_requests', 0)} "
          f"sheds={sum(sheds.values())} ({shed_txt}) "
          f"replacements=prefill:{repl.get('prefill', 0)}"
          f"/decode:{repl.get('decode', 0)} "
          f"breaker_trips={totals.get('breaker_trips', 0)} "
          f"drains_reaped={totals.get('drains_reaped', 0)}")
    for key, r in sorted((st.get("routers") or {}).items()):
        rec = (r.get("recent_failover_recovery_ms") or {})
        rfo = r.get("failovers") or {}
        rsh = r.get("sheds_by_cause") or {}
        rsh_txt = ", ".join(f"{k}:{v}" for k, v in sorted(rsh.items()))
        print(f"  {key}: failovers=pf:{rfo.get('prefill', 0)}"
              f"/dec:{rfo.get('decode', 0)} "
              f"failed_over_reqs={r.get('failover_requests', 0)} "
              "sheds={" + rsh_txt + "}"
              + (f" recovery_p50={rec.get('p50', 0.0):.0f}ms"
                 if rec.get("n") else ""))
    for key, h in sorted((st.get("healers") or {}).items()):
        d = h.get("deaths") or {}
        rp = h.get("replacements") or {}
        print(f"  {key}: deaths=pf:{d.get('prefill', 0)}"
              f"/dec:{d.get('decode', 0)} "
              f"replacements=pf:{rp.get('prefill', 0)}"
              f"/dec:{rp.get('decode', 0)} "
              f"blocked={h.get('replacements_blocked', 0)} "
              f"breaker_trips={h.get('breaker_trips', 0)} "
              f"breaker_open={h.get('breaker_open') or []} "
              f"drains_reaped={h.get('drains_reaped', 0)}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_servefault_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_gateway(args) -> None:
    """`ray_tpu gateway` — HTTP front-door view (serve/gateway.py):
    per-replica request counters split by priority class and status
    code, recent TTFT per class, QoS admission/rejection, batch-slot
    preemptions, plus the cluster totals every other surface (state
    API, /api/gateway, Prometheus, `gateway` timeline lane) reports
    from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.gateway_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    if not st.get("gateways"):
        print("no gateway telemetry recorded (is a GatewayServer "
              "running?)")
        return
    totals = st.get("totals") or {}
    code_txt = " ".join(
        f"{k}:{v}"
        for k, v in sorted((totals.get("by_code") or {}).items())) \
        or "none"
    print(f"totals: gateways={totals.get('gateways', 0)} "
          f"accepted={totals.get('accepted', 0)} "
          f"completed={totals.get('completed', 0)} "
          f"(streamed {totals.get('streamed', 0)}) "
          f"tokens_out={totals.get('tokens_out', 0)} "
          f"rate_limited={totals.get('rate_limited', 0)} "
          f"sheds={totals.get('sheds', 0)} "
          f"disconnects={totals.get('disconnects', 0)} "
          f"preemptions={totals.get('preemptions', 0)} "
          f"codes=({code_txt})")
    for cls, row in sorted((totals.get("by_class") or {}).items()):
        print(f"  class {cls}: accepted={row.get('accepted', 0)} "
              f"completed={row.get('completed', 0)} "
              f"shed={row.get('shed', 0)} "
              f"disconnects={row.get('disconnects', 0)}")
    for key, g in sorted((st.get("gateways") or {}).items()):
        ttft = g.get("ttft_ms") or {}
        ttft_txt = " ".join(
            f"{c}_p99={w.get('p99', 0.0):.0f}ms"
            for c, w in sorted(ttft.items()) if w.get("n"))
        print(f"  {key}: {g.get('host')}:{g.get('port')} "
              f"models={','.join(g.get('models') or [])} "
              f"accepted={g.get('accepted', 0)} "
              f"completed={g.get('completed', 0)} "
              f"disconnects={g.get('disconnects', 0)} "
              f"sheds={g.get('sheds', 0)} "
              f"rate_limited={g.get('rate_limited', 0)} "
              f"preemptions={g.get('preemptions', 0)}"
              + (f" {ttft_txt}" if ttft_txt else ""))
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_gateway_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_requests(args) -> None:
    """`ray_tpu requests` — per-request flight-recorder view
    (observability/requests.py): retention totals, the cluster-wide
    slowest requests with their per-phase latency breakdowns, and the
    p99-attribution report naming the phase that owns the tail —
    from the same aggregate every other surface (state API,
    /api/requesttrace, Prometheus, `requests` timeline lane) reads.
    `--trace <id>` replays one kept request's full span log."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    if args.trace:
        trc = state.request_trace(args.trace)
        if trc is None:
            print(f"no kept trace for request {args.trace!r} "
                  f"(sampled out, aged out, or never recorded)")
            return
        if args.json:
            print(json.dumps(trc, indent=2, default=str))
            return
        print(f"{trc.get('request_id')}: outcome={trc.get('outcome')} "
              f"total={trc.get('total_ms', 0.0):.1f}ms "
              f"attempts={trc.get('attempts', 1)} "
              f"preempts={trc.get('preempts', 0)} "
              f"source={trc.get('source')} "
              f"class={trc.get('class', '-')} "
              f"tenant={trc.get('tenant', '-')}")
        for ph in trc.get("phases") or []:
            extra = " ".join(
                f"{k}={v}" for k, v in sorted(ph.items())
                if k not in ("phase", "t_ms", "dur_ms", "attempt")
                and v is not None)
            print(f"  [a{ph.get('attempt', 1)}] "
                  f"{ph.get('phase'):<18} +{ph.get('t_ms', 0.0):9.1f}ms "
                  f"dur={ph.get('dur_ms', 0.0):9.2f}ms"
                  + (f"  {extra}" if extra else ""))
        for ph in trc.get("remote_phases") or []:
            print(f"  [a{ph.get('attempt', 1)}] "
                  f"{ph.get('phase'):<18} (remote) "
                  f"dur={ph.get('dur_ms', 0.0):9.2f}ms "
                  f"server={ph.get('server', '-')}")
        return
    st = state.requesttrace_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    if not st.get("stores"):
        print("no request-trace telemetry recorded (serve traffic "
              "with RAY_TPU_REQTRACE=1 — the default — first)")
        return
    totals = st.get("totals") or {}
    out_txt = " ".join(
        f"{k}:{v}"
        for k, v in sorted((totals.get("outcomes") or {}).items())) \
        or "none"
    print(f"totals: stores={totals.get('stores', 0)} "
          f"completed={totals.get('completed', 0)} "
          f"kept={totals.get('kept', 0)} "
          f"dropped={totals.get('dropped', 0)} "
          f"replayed={totals.get('replayed_requests', 0)} "
          f"preempted={totals.get('preempted_requests', 0)} "
          f"slowest={totals.get('slowest_ms', 0.0):.1f}ms "
          f"outcomes=({out_txt})")
    attr = st.get("attribution") or {}
    if attr.get("n"):
        owner = attr.get("tail_owner")
        share = attr.get("tail_share")
        print(f"p99 attribution over {attr['n']} requests: "
              f"p50={attr.get('p50_total_ms', 0.0):.1f}ms "
              f"p99={attr.get('p99_total_ms', 0.0):.1f}ms tail_owner="
              + (f"{owner} ({share:.0%} of the gap)"
                 if owner else "none"))
        for ph, row in sorted((attr.get("phases") or {}).items(),
                              key=lambda kv: -kv[1]["delta_ms"]):
            print(f"    {ph:<18} p50={row['p50_ms']:9.2f}ms "
                  f"p99={row['p99_ms']:9.2f}ms "
                  f"delta={row['delta_ms']:+9.2f}ms")
    k = max(1, int(args.slowest))
    for rec in (st.get("slowest") or [])[:k]:
        pm = rec.get("phase_ms") or {}
        ph_txt = " ".join(f"{p}={pm[p]:.1f}" for p in sorted(
            pm, key=lambda p: -pm[p]))
        print(f"  {rec.get('request_id')}: "
              f"{rec.get('total_ms', 0.0):.1f}ms "
              f"outcome={rec.get('outcome')} "
              f"attempts={rec.get('attempts', 1)}"
              + (f"  [{ph_txt}]" if ph_txt else ""))
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_requesttrace_events",
                                  args.events, timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_lora(args) -> None:
    """`ray_tpu lora` — multi-tenant LoRA serving view
    (serve/lora.py): per-pool adapter-paging counters and residents,
    per-tenant request counters, plus the cluster totals every other
    surface (state API, /api/lora, Prometheus, `lora` timeline lane)
    reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.lora_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    if not (st.get("pools") or st.get("routers")):
        print("no lora telemetry recorded (is an AdapterPool-backed "
              "replica running?)")
        return
    totals = st.get("totals") or {}
    print(f"totals: pools={totals.get('pools', 0)} "
          f"slots={totals.get('slots', 0)} "
          f"resident={totals.get('resident', 0)} "
          f"pinned={totals.get('pinned', 0)} "
          f"acquires={totals.get('acquires', 0)} "
          f"hit_rate={totals.get('hit_rate', 0.0):.2%} "
          f"misses={totals.get('misses', 0)} "
          f"evictions={totals.get('evictions', 0)} "
          f"swaps={totals.get('swaps', 0)} "
          f"page_in={totals.get('page_in_bytes', 0)}B "
          f"tenants={totals.get('tenants', 0)}")
    for key, p in sorted((st.get("pools") or {}).items()):
        print(f"  pool {key}: slots={p.get('slots')} "
              f"resident={p.get('resident')} "
              f"pinned={p.get('pinned')} "
              f"hits={p.get('hits')} misses={p.get('misses')} "
              f"evictions={p.get('evictions')} "
              f"swaps={p.get('swaps')} "
              f"rank_max={p.get('rank_max')}")
    tenants = st.get("tenants") or {}
    for t, ts in sorted(tenants.items()):
        print(f"  tenant {t}: dispatched={ts.get('dispatched', 0)} "
              f"completed={ts.get('completed', 0)} "
              f"shed={ts.get('shed', 0)} "
              f"slo_misses={ts.get('slo_misses', 0)} "
              f"pool_hits={ts.get('hits', 0)}/"
              f"misses={ts.get('misses', 0)} "
              f"swaps={ts.get('swaps', 0)}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_lora_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_autoscale(args) -> None:
    """`ray_tpu autoscale` — serving-autoscaler view
    (serve/autoscale.py): per-loop tier targets, decision counts,
    drain outcomes, and replica-seconds, plus the cluster totals every
    other surface (state API, /api/autoscale, Prometheus, timeline
    markers) reports from the same snapshots."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.autoscaler_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    loops = st.get("autoscalers") or {}
    if not loops:
        print("no autoscaler telemetry recorded (is a "
              "serve.autoscale.DisaggAutoscaler running?)")
        return
    totals = st.get("totals") or {}
    rs = totals.get("replica_seconds") or {}
    print(f"totals: scale_ups={totals.get('scale_ups', 0)} "
          f"scale_downs={totals.get('scale_downs', 0)} "
          f"drains={totals.get('drains_completed', 0)} "
          f"(forced {totals.get('drains_forced', 0)}) "
          f"replica_s=prefill:{rs.get('prefill', 0.0):.1f}"
          f"/decode:{rs.get('decode', 0.0):.1f}")
    for key, s in sorted(loops.items()):
        print(f"  {key}: router={s.get('router')} "
              f"target_p99={s.get('target_p99_ms')}ms")
        for tier in ("prefill", "decode"):
            bounds = s.get(f"{tier}_bounds") or ["?", "?"]
            print(f"    {tier}: active={s.get(f'{tier}_active', 0)}"
                  f"/{s.get(f'{tier}_replicas', 0)} "
                  f"bounds=[{bounds[0]},{bounds[1]}] "
                  f"ups={(s.get('scale_ups') or {}).get(tier, 0)} "
                  f"downs={(s.get('scale_downs') or {}).get(tier, 0)} "
                  f"last={(s.get('last_reason') or {}).get(tier, '')!r}")
        if s.get("draining"):
            for d in s["draining"]:
                print(f"    DRAINING {d.get('tier')}:{d.get('rid')}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_autoscale_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_oracle(args) -> None:
    """`ray_tpu oracle` — step-time oracle view (observability.roofline):
    the latest roofline prediction per layout, the predicted-vs-measured
    validation tail (per-phase residuals, fitted calibration), plus the
    totals every other surface (state API, /api/oracle, Prometheus,
    timeline counter track) reports from the same aggregate."""
    _connect(args)
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.util import state

    st = state.oracle_status()
    if args.json:
        print(json.dumps(st, indent=2, default=str))
        return
    preds = st.get("predictions") or {}
    vals = st.get("validations") or []
    if not preds and not vals:
        print("no oracle telemetry recorded (run `ray_tpu analyze "
              "--predict-step-time` or roofline.record_prediction / "
              "validate_run)")
        return
    totals = st.get("totals") or {}
    cal = totals.get("last_calibration")
    worst = totals.get("worst_residual_ratio")
    print(f"totals: layouts={totals.get('layouts', 0)} "
          f"validations={totals.get('validations', 0)}"
          + (f" last_calibration={cal:.3f}" if cal is not None else "")
          + (f" worst_residual={worst:.2f}x" if worst is not None
             else ""))
    for layout, p in sorted(preds.items()):
        print(f"  {layout}: predicted="
              f"{p.get('predicted_step_ms', 0.0):.3f}ms "
              f"(device={p.get('device_step_ms', 0.0):.3f} "
              f"ici={p.get('ici_wait_ms', 0.0):.3f} "
              f"dcn={p.get('dcn_wait_ms', 0.0):.3f}) "
              f"dcn_bytes={p.get('dcn_bytes', 0):.0f}"
              + (" UNMODELED:" + ",".join(p["unmodeled_collectives"])
                 if p.get("unmodeled_collectives") else ""))
    for v in vals[-5:]:
        res = " ".join(f"{k}={r:.2f}x" for k, r
                       in (v.get("residuals") or {}).items())
        print(f"  validation run={v.get('run_id')} "
              f"layout={v.get('layout')} steps={v.get('n_steps')} "
              f"calibration={v.get('calibration', 1.0):.3f} {res}")
    if args.events:
        w = worker_mod.global_worker
        events = w.conductor.call("get_oracle_events", args.events,
                                  timeout=10.0)
        _print_event_tail(events, args.events)


def cmd_metrics(args) -> None:
    _connect(args)
    from ray_tpu.util import state

    sys.stdout.write(state.prometheus_metrics())


def cmd_dashboard(args) -> None:
    from ray_tpu.dashboard import main as dash_main

    dash_main(["--address", _resolve_address(args.address),
               "--host", args.host, "--port", str(args.port)])


def cmd_config(args) -> None:
    """Print the flag table (ray_config_def.h analog) with live values."""
    from ray_tpu._private.config import config

    rows = config.describe()
    w = max(len(r["env_var"]) for r in rows)
    for r in rows:
        mark = "*" if r["source"] == "env" else " "
        print(f"{mark} {r['env_var']:<{w}}  {r['type']:<5} "
              f"= {r['value']!r:<14} {r['doc']}")
    print("\n(* = overridden via environment / _system_config)")


def cmd_microbench(args) -> None:
    from ray_tpu._private import perf

    perf.run(scale=args.scale, out=args.out)


def cmd_job(args) -> None:
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient(_resolve_address(args.address))
    if args.job_cmd == "submit":
        import shlex

        tokens = args.entrypoint
        if tokens and tokens[0] == "--":  # REMAINDER keeps the separator
            tokens = tokens[1:]
        job_id = client.submit_job(
            entrypoint=" ".join(shlex.quote(t) for t in tokens))
        print(job_id)
        if args.wait:
            status = client.wait_until_finished(job_id, timeout=args.timeout)
            sys.stdout.write(client.get_job_logs(job_id))
            print(f"job {job_id}: {status}")
            if status != "SUCCEEDED":
                raise SystemExit(1)
    elif args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
    elif args.job_cmd == "logs":
        sys.stdout.write(client.get_job_logs(args.job_id))
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.job_id) else "not running")
    elif args.job_cmd == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))


def cmd_analyze(args) -> None:
    """`ray_tpu analyze` — shardlint static analysis: AST lint over
    Python sources (blocking-in-async, host-sync-in-jit) plus, with
    --layouts, the shard/collective/DCN-cost checks over the built-in
    dryrun mesh layouts. Fully deviceless: jax is pinned to cpu and no
    backend device is ever enumerated, so a wedged TPU relay cannot hang
    the lint."""
    # Force the cpu platform BEFORE anything imports jax: the layout
    # checks trace against AbstractMesh and never need silicon. Restored
    # on exit so programmatic main([...]) callers (and their subprocess
    # children) are not pinned to cpu afterwards.
    prev_platform = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        _run_analyze(args)
    finally:
        if prev_platform is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = prev_platform


def _format_predictions(preds: dict) -> str:
    lines = ["predicted step time per layout (roofline, "
             "compile-excluded; observability.roofline):"]
    for name, p in sorted(preds.items()):
        extra = ""
        if p.get("unmodeled_collectives"):
            extra = (" [unmodeled: "
                     + ", ".join(p["unmodeled_collectives"]) + "]")
        lines.append(
            f"  {name:<14} {p['predicted_step_ms']:>10.4f} ms  "
            f"(device {p['device_step_ms']:.4f} + "
            f"ici {p['ici_wait_ms']:.4f} + "
            f"dcn {p['dcn_wait_ms']:.4f})  "
            f"dcn {p['dcn_bytes'] / 2 ** 20:.2f} MiB/step{extra}")
    return "\n".join(lines)


def _nearest_readme(root: str) -> "str | None":
    """README.md beside the analyzed tree or up to two levels above it
    (the package dir's README lives at the repo root) — feeds the
    env-knob-undocumented check; None skips that rule."""
    d = os.path.abspath(root)
    for _ in range(3):
        cand = os.path.join(d, "README.md")
        if os.path.exists(cand):
            try:
                with open(cand) as f:
                    return f.read()
            except OSError:
                return None
        parent = os.path.dirname(d)
        if parent == d:
            break
        d = parent
    return None


def _run_analyze(args) -> None:
    from ray_tpu import analysis

    findings = []
    paths = args.paths
    if not paths:
        # --layouts is additive ("also analyze ..."): the source lint of
        # the installed package always runs unless explicit paths narrow
        # it.
        import ray_tpu

        paths = [os.path.dirname(os.path.abspath(ray_tpu.__file__))]
    for p in paths:
        if not os.path.exists(p):
            raise SystemExit(f"no such file or directory: {p}")
        findings.extend(analysis.lint_path(p))
    want_knobs = getattr(args, "knob_table", False)
    knob_rows = None
    if getattr(args, "invariants", False) or want_knobs:
        for p in paths:
            root = p if os.path.isdir(p) else (os.path.dirname(p) or ".")
            if getattr(args, "invariants", False):
                findings.extend(analysis.analyze_invariants(
                    root, readme_text=_nearest_readme(root)))
            if want_knobs:
                rows = analysis.knob_table(
                    analysis.collect_env_reads(root))
                knob_rows = (knob_rows or []) + rows
    predict = getattr(args, "predict_step_time", False)
    predictions = None
    if args.layouts or predict:
        # If jax first loads HERE, it initializes under our forced
        # JAX_PLATFORMS=cpu — its config value is our pin, not the
        # caller's, so restore to None (auto-detect), not to `prev`.
        jax_preloaded = "jax" in sys.modules
        import jax

        # config (not just env) pin: the axon sitecustomize force-sets
        # JAX_PLATFORMS, and config wins regardless. Restored so a
        # programmatic main([...]) caller is not left cpu-pinned.
        prev = jax.config.jax_platforms if jax_preloaded else None
        jax.config.update("jax_platforms", "cpu")
        try:
            if args.layouts:
                for name, fs in \
                        analysis.analyze_builtin_layouts().items():
                    findings.extend(fs)
            if predict:
                from ray_tpu.observability import roofline

                predictions = roofline.predict_builtin_layouts()
        finally:
            jax.config.update("jax_platforms", prev)
    sorted_findings = [f.to_dict() for f in
                       analysis.sort_findings(findings)]
    if args.json:
        # plain --json keeps the historical bare findings list; the
        # predictions / knob table ride in a wrapper object only when
        # asked for
        if predictions is not None or knob_rows is not None:
            payload = {"findings": sorted_findings}
            if predictions is not None:
                payload["predicted_step_time"] = predictions
            if knob_rows is not None:
                payload["env_knobs"] = knob_rows
            print(json.dumps(payload, indent=2))
        else:
            print(json.dumps(sorted_findings, indent=2))
    else:
        print(analysis.format_report(findings))
        if predictions is not None:
            print(_format_predictions(predictions))
        if knob_rows is not None:
            print(analysis.format_knob_table(knob_rows))
    worst = analysis.max_severity(findings)
    order = list(analysis.SEVERITIES)
    if findings and order.index(worst) <= order.index(args.fail_on):
        raise SystemExit(1)


def cmd_serve(args) -> None:
    """`serve run|deploy|status|config|shutdown|delete` — reference
    python/ray/serve/scripts.py:147-746 (run/deploy/config/status) over
    the declarative YAML schema (serve/schema.py)."""
    _connect(args)
    from ray_tpu import serve
    from ray_tpu.serve.schema import (ServeDeploySchema, deploy_config,
                                      get_deployed_config)

    if args.serve_cmd in ("run", "deploy"):
        if args.config_or_import.endswith((".yaml", ".yml")):
            schema = ServeDeploySchema.from_yaml_file(args.config_or_import)
        else:
            # bare import path: one app with defaults
            schema = ServeDeploySchema.from_dict({"applications": [
                {"import_path": args.config_or_import}]})
        names = deploy_config(schema)
        print(f"deployed application(s): {', '.join(names)}")
        addr = serve.proxy_address()
        if addr:
            print(f"HTTP ingress at http://{addr[0]}:{addr[1]}")
        if args.serve_cmd == "run":
            # reference `serve run` stays attached and tears down on ^C
            import time as _t

            try:
                while True:
                    _t.sleep(3600)
            except KeyboardInterrupt:
                for name in names:
                    serve.delete(name)
                print("applications deleted")
    elif args.serve_cmd == "status":
        try:
            print(json.dumps(serve.status(), indent=2, default=str))
        except RuntimeError as e:
            print(json.dumps({"applications": {}, "error": str(e)}))
    elif args.serve_cmd == "config":
        cfg = get_deployed_config()
        if cfg is None:
            print("no config deployed (code-deployed apps have no "
                  "declarative config)")
        else:
            import yaml

            sys.stdout.write(yaml.safe_dump(cfg, sort_keys=False))
    elif args.serve_cmd == "delete":
        serve.delete(args.name)
        print(f"application {args.name!r} deleted")
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="ray_tpu", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("start",
                        help="start a head node or join as worker host")
    sp.add_argument("--head", action="store_true")
    sp.add_argument("--address", help="head host:port to join (worker host)")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=0)
    sp.add_argument("--num-cpus", type=float,
                    default=float(os.cpu_count() or 1))
    sp.add_argument("--resources", help='extra resources as JSON, e.g. '
                    '\'{"TPU": 4}\'')
    sp.add_argument("--node-id", help="pre-assigned node id (worker-host "
                                      "joins launched by a provider)")
    sp.add_argument("--block", action="store_true")
    sp.add_argument("--dashboard-port", type=int, default=8265)
    sp.add_argument("--no-dashboard", action="store_true")
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("dashboard", help="serve the web dashboard for a "
                        "running cluster")
    sp.add_argument("--address", help="conductor host:port")
    sp.add_argument("--host", default="127.0.0.1")
    sp.add_argument("--port", type=int, default=8265)
    sp.set_defaults(fn=cmd_dashboard)

    for name, fn in [("stop", cmd_stop), ("status", cmd_status),
                     ("summary", cmd_summary), ("memory", cmd_memory),
                     ("metrics", cmd_metrics)]:
        sp = sub.add_parser(name)
        sp.add_argument("--address")
        sp.set_defaults(fn=fn)

    sp = sub.add_parser("config", help="show the runtime flag table")
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("list", help="list cluster entities")
    sp.add_argument("kind", choices=["nodes", "workers", "actors", "tasks",
                                     "objects", "placement-groups"])
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("timeline", help="export chrome trace")
    sp.add_argument("--output", default="ray_tpu_timeline.json")
    sp.add_argument("--merged", action="store_true",
                    help="one unified trace: task events + tracing spans "
                         "+ training step markers (flight recorder)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("train-status",
                        help="gang training telemetry: per-rank step "
                             "stats, MFU, skew, stragglers")
    sp.add_argument("--run", help="filter to one run id")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_train_status)

    sp = sub.add_parser("resilience-status",
                        help="recovery subsystem: quarantined/draining "
                             "hosts, failure scores, restart/preemption "
                             "counters, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=10,
                    help="recent events to print (default 10)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_resilience_status)

    sp = sub.add_parser("weights",
                        help="live weight fabric: published versions, "
                             "manifests, keep-last-K GC")
    # --address lives on the LEAF parsers only: a mid-level flag would
    # be clobbered by the leaf's default (None) and silently ignored
    wsub = sp.add_subparsers(dest="weights_cmd", required=True)
    ws = wsub.add_parser("list", help="versions per weight-set name")
    ws.add_argument("--name", help="filter to one weight set")
    ws.add_argument("--json", action="store_true")
    ws.add_argument("--address")
    ws = wsub.add_parser("inspect",
                         help="one version's manifest (metadata only)")
    ws.add_argument("name")
    ws.add_argument("--version", type=int,
                    help="default: latest committed")
    ws.add_argument("--address")
    ws = wsub.add_parser("gc", help="keep only the newest K versions")
    ws.add_argument("name")
    ws.add_argument("--keep", type=int, required=True)
    ws.add_argument("--address")
    sp.set_defaults(fn=cmd_weights)

    sp = sub.add_parser("kvcache",
                        help="paged KV prefix cache: per-engine "
                             "hit/miss/eviction stats, pool "
                             "utilization, recent events")
    sp.add_argument("--engine", help="filter to one engine id")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N cache events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_kvcache)

    sp = sub.add_parser("speculate",
                        help="speculative decoding: per-engine draft "
                             "proposal/acceptance counters, "
                             "tokens-per-verify, int8-KV flag")
    sp.add_argument("--engine", help="filter to one engine id")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N spec_accept/"
                         "spec_reject markers")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_speculate)

    sp = sub.add_parser("pipeline",
                        help="MPMD pipelines: stage registry, per-stage "
                             "bubble fraction and channel bytes, "
                             "recent events")
    sp.add_argument("--name", help="filter to one pipeline")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N pipeline events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_pipeline)

    sp = sub.add_parser("online",
                        help="online learning loop: per-sampler "
                             "rollout/staleness stats, buffer "
                             "occupancy, learner ingest, recent "
                             "events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N online-loop events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_online)

    sp = sub.add_parser("disagg",
                        help="disaggregated prefill/decode serving: "
                             "KV-transfer accounting (shm vs rpc), "
                             "router shed/queue depth, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N disagg events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_disagg)

    sp = sub.add_parser("kvplane",
                        help="global KV plane: tiered prefix cache "
                             "(HBM -> host arena -> object store), "
                             "spill/re-adopt accounting, prefix "
                             "directory routing, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N kvplane events "
                         "(spill/tier2_hit/tier3_publish/tier3_adopt/"
                         "directory_hit markers)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_kvplane)

    sp = sub.add_parser("servefault",
                        help="serving-plane fault tolerance: request "
                             "failovers by phase, sheds by cause, "
                             "replica deaths/replacements, breaker "
                             "state, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N servefault events "
                         "(the resilience lane's failover/replace/"
                         "breaker_trip slice)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_servefault)

    sp = sub.add_parser("gateway",
                        help="HTTP front door: per-replica request "
                             "counters by priority class and status "
                             "code, recent TTFT, QoS admissions, "
                             "batch-slot preemptions, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N gateway events "
                         "(accept/first_byte/preempt/rate_limit/"
                         "disconnect markers)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_gateway)

    sp = sub.add_parser("requests",
                        help="per-request flight recorder: slowest "
                             "requests with per-phase breakdowns, "
                             "p99 tail attribution, single-trace "
                             "replay by request id")
    sp.add_argument("--slowest", type=int, default=10,
                    help="print the K slowest kept requests "
                         "(default 10)")
    sp.add_argument("--trace",
                    help="replay ONE kept request's phase spans by "
                         "request id")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N request-trace events "
                         "(kept-trace + remote-phase records)")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_requests)

    sp = sub.add_parser("lora",
                        help="multi-tenant LoRA serving: adapter-pool "
                             "paging (hits/misses/evictions/swaps, "
                             "residents), per-tenant request counters, "
                             "recent page_in/evict/swap events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N lora events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_lora)

    sp = sub.add_parser("autoscale",
                        help="serving autoscaler: per-tier targets and "
                             "decision counts, drain outcomes, "
                             "replica-seconds, recent events")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N autoscale events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_autoscale)

    sp = sub.add_parser("oracle",
                        help="step-time oracle: roofline predictions "
                             "per layout, predicted-vs-measured "
                             "residuals, fitted calibration")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("--events", type=int, default=0,
                    help="also print the last N oracle events")
    sp.add_argument("--address")
    sp.set_defaults(fn=cmd_oracle)

    sp = sub.add_parser("microbench",
                        help="core-runtime micro benchmarks (ray_perf "
                             "analog): task/actor/put-get/queue/churn")
    sp.add_argument("--scale", type=float, default=1.0)
    sp.add_argument("--out", default="")
    sp.set_defaults(fn=cmd_microbench)

    sp = sub.add_parser("analyze",
                        help="shardlint static analysis: AST lint over "
                             "sources, --layouts for mesh/DCN checks")
    sp.add_argument("paths", nargs="*",
                    help="files/directories to lint (default: the "
                         "installed ray_tpu package)")
    sp.add_argument("--layouts", action="store_true",
                    help="also analyze the built-in dryrun mesh layouts "
                         "(sharding specs, collectives over DCN)")
    sp.add_argument("--predict-step-time", action="store_true",
                    help="also print the step-time oracle's roofline "
                         "prediction (device/ici/dcn breakdown) per "
                         "built-in dryrun layout")
    sp.add_argument("--invariants", action="store_true",
                    help="also run the cross-module invariant engine "
                         "(lock discipline, surface parity, env-knob "
                         "registry, donation audit)")
    sp.add_argument("--knob-table", action="store_true",
                    help="print the canonical RAY_TPU_* env-knob table "
                         "from the registry (markdown; rides the JSON "
                         "wrapper as env_knobs with --json)")
    sp.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    sp.add_argument("--fail-on", choices=["error", "warning", "info"],
                    default="error",
                    help="exit 1 when a finding at this severity or "
                         "worse exists (default: error)")
    sp.set_defaults(fn=cmd_analyze)

    sp = sub.add_parser("serve", help="Serve applications: run/deploy from "
                                      "YAML config, status, shutdown")
    sp.add_argument("--address")
    ssub = sp.add_subparsers(dest="serve_cmd", required=True)
    for sc in ("run", "deploy"):
        s = ssub.add_parser(sc, help="deploy apps from a YAML config or a "
                                     "module:attr import path"
                                     + (" and stay attached"
                                        if sc == "run" else ""))
        s.add_argument("config_or_import",
                       help="path/to/config.yaml or module:application")
    ssub.add_parser("status")
    ssub.add_parser("config", help="echo the last deployed YAML config")
    s = ssub.add_parser("delete")
    s.add_argument("name")
    ssub.add_parser("shutdown")
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("job", help="job submission")
    sp.add_argument("--address")
    jsub = sp.add_subparsers(dest="job_cmd", required=True)
    j = jsub.add_parser("submit")
    j.add_argument("--wait", action="store_true")
    j.add_argument("--timeout", type=float, default=600.0)
    j.add_argument("entrypoint", nargs=argparse.REMAINDER)
    for jc in ["status", "logs", "stop"]:
        j = jsub.add_parser(jc)
        j.add_argument("job_id")
    jsub.add_parser("list")
    sp.set_defaults(fn=cmd_job)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
