"""ray_tpu.weights — the live weight fabric.

Versioned, sharded, in-memory train→serve weight publication:

- **Producer** (:class:`WeightPublisher` / :func:`publish`): each host
  publishes only its LOCAL shards as chunks in its own object store
  (shm for same-host readers, chunked RPC for remote) plus a
  metadata-only fragment to the conductor's version registry. No
  single-host gather, ever.
- **Registry** (conductor-side): commits a version atomically when the
  last host's fragment lands, keeps the newest K versions
  (``weights_keep``), reaps publishes torn by a producer death
  (``weights_publish_ttl_s``), and notifies producers to free dropped
  chunks over the ``weights`` pubsub channel.
- **Consumer** (:class:`WeightSubscriber`): reshard-on-fetch — each
  device materializes only the slices its target sharding needs, the
  same ``restore(like=)`` contract as async checkpointing, so a
  dp/fsdp training layout feeds a tp serving layout with no
  intermediate full array on any host.
- **Serving** (:class:`WeightSync`): subscribes a continuous-batching
  engine and hot-swaps params BETWEEN decode ticks; in-flight requests
  keep their KV caches and finish. Staleness is a Prometheus gauge.

Surfaces: ``util.state.weight_versions()``, ``ray_tpu weights``
(list/inspect/gc), dashboard ``/api/weights``, publish/fetch/swap
markers in the merged timeline.
"""
from .publisher import (WeightPublisher, leaf_content_hashes,  # noqa: F401
                        publish)
from .subscriber import FetchStats, WeightSubscriber  # noqa: F401
from .sync import WeightSync  # noqa: F401

__all__ = ["WeightPublisher", "WeightSubscriber", "WeightSync",
           "FetchStats", "leaf_content_hashes", "publish"]
