"""Consumer side of the live weight fabric: reshard-on-fetch.

A fetch pulls ONLY the chunks the target sharding needs and assembles
each device's shard with ``jax.make_array_from_callback`` — the exact
``restore(like=)`` contract (the assembly IS
``async_checkpoint._LeafReader`` with a chunk-fetching loader), so a
dp/fsdp training layout feeds a tp inference layout with no intermediate
full array on any host.

Per-fetch accounting (:class:`FetchStats`) records bytes pulled over the
object plane and the largest single slice any read materialized — the
e2e acceptance asserts from these that no process ever assembled a full
unsharded copy of a sharded leaf.
"""
from __future__ import annotations

import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.train.async_checkpoint import _LeafReader, materialize_like
from ray_tpu.util import chunks

from ._common import require_worker
from .metrics import weight_metrics


def _worker():
    return require_worker("fetching weights")


@dataclass
class FetchStats:
    """Accounting for one fetch() call."""

    version: int = 0
    chunks_fetched: int = 0        # pulled over the object plane
    chunks_local: int = 0          # already in this process's store
    fetched_bytes: int = 0         # bytes pulled over the object plane
    shm_bytes: int = 0             # ...of which same-host (shm path)
    rpc_bytes: int = 0             # ...of which true cross-host RPC
    max_read_bytes: int = 0        # largest single assembled slice
    # per-leaf: (largest single read, full leaf nbytes) — the
    # no-full-copy assertion compares these for sharded leaves
    leaf_read_bytes: List[Any] = field(default_factory=list)
    elapsed_s: float = 0.0
    # delta-publication provenance of the fetched manifest
    delta: bool = False
    base_version: Optional[int] = None
    changed_leaves: Optional[List[int]] = None


class _ChunkFetcher(chunks.ChunkFetcher):
    """Shared chunked-transfer fetcher (util.chunks) feeding this
    fetch's :class:`FetchStats` — each needed chunk crosses the object
    plane at most once per fetch, with remote-vs-local (and shm-vs-RPC)
    accounting."""

    def __init__(self, worker, stats: FetchStats, seed_cache=None):
        def on_read(nbytes: int, was_local: bool, same_host: bool,
                    _stats=stats) -> None:
            if was_local:
                _stats.chunks_local += 1
            else:
                _stats.chunks_fetched += 1
                _stats.fetched_bytes += nbytes
                if same_host:
                    _stats.shm_bytes += nbytes
                else:
                    _stats.rpc_bytes += nbytes

        super().__init__(worker, timeout=60.0, on_read=on_read,
                         seed_cache=seed_cache, caller="weights")


class _AccountingReader(_LeafReader):
    """_LeafReader that records the size of every assembled slice."""

    def __init__(self, shape, dtype, shards, loader, stats: FetchStats,
                 leaf_index: int):
        super().__init__(None, shape, dtype, shards, loader=loader)
        self._stats = stats
        self._leaf_index = leaf_index

    def read(self, index):
        out = super().read(index)
        nbytes = int(out.nbytes)
        self._stats.max_read_bytes = max(self._stats.max_read_bytes,
                                         nbytes)
        rec = self._stats.leaf_read_bytes[self._leaf_index]
        rec["max_read_bytes"] = max(rec["max_read_bytes"], nbytes)
        return out


class WeightSubscriber:
    """Fetches versions of one named weight set into this process.

    Rides the `weights` pubsub channel for publish notifications (with a
    registry poll as the fallback path); :meth:`fetch` pulls a version
    under a target sharding template.
    """

    def __init__(self, name: str = "default", *,
                 cache_chunks: bool = False):
        self.name = name
        self._worker = _worker()
        self._cv = threading.Condition()
        self.last_stats: Optional[FetchStats] = None
        # (version, {object_id: host array}) — holding the arrays is
        # what keeps the bytes at hand (a bare local-store entry would
        # be refcount-freed the moment the pulling fetcher's refs die);
        # fetch() seeds its chunk cache from this. Retention costs a
        # host copy of the model, so it is OPT-IN: `cache_chunks=True`
        # at construction, or implied by the first prefetch() call
        # (the prefetch/delta workflow is what profits from it).
        self._cache_chunks = bool(cache_chunks)
        self._prefetched: Optional[tuple] = None
        # guards _prefetched: the pubsub prefetch thread and the sync
        # loop's fetch both publish results; a version must never
        # CLOBBER a newer one's already-pulled chunks
        self._pf_lock = threading.Lock()
        self._worker.subscribe_channel("weights", self._on_weights_msg)

    def _store_prefetched(self, version: int,
                          cache: Dict[str, Any]) -> None:
        """Publish pulled chunks, newest version wins: an older
        completion merges its entries UNDER a newer holder's (the
        newer version's unchanged chunks may be the very arrays the
        older pull produced) instead of discarding them."""
        with self._pf_lock:
            cur = self._prefetched
            if cur is not None and cur[0] > version:
                self._prefetched = (cur[0], {**cache, **cur[1]})
            else:
                self._prefetched = (version, cache)

    def _on_weights_msg(self, msg: Any) -> None:
        """Pure wakeup: waiters re-poll the registry, which stays the
        single source of truth for what is actually committed."""
        if not isinstance(msg, dict) or msg.get("name") != self.name:
            return
        if msg.get("kind") == "published":
            with self._cv:
                self._cv.notify_all()

    # ------------------------------------------------------------ queries

    def latest_version(self) -> Optional[int]:
        """Latest committed version in the registry, or None. An O(1)
        RPC — polled at staleness-check cadence by every replica, so it
        must not ship the manifest's chunk tables each time."""
        v = self._worker.conductor.call("weights_latest_version",
                                        self.name, timeout=30.0)
        return None if v is None else int(v)

    def wait_for_version(self, min_version: int,
                         timeout: float = 30.0) -> int:
        """Block until a version >= min_version is committed; returns
        the latest version. Pubsub-driven with a bounded registry poll
        as the safety net (a conductor restart drops subscriptions)."""
        deadline = time.monotonic() + timeout
        while True:
            latest = self.latest_version()
            if latest is not None and latest >= min_version:
                return latest
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no version >= {min_version} of {self.name!r} "
                    f"within {timeout}s (latest: {latest})")
            with self._cv:
                self._cv.wait(min(remaining, 0.5))

    # ----------------------------------------------------------- prefetch

    def prefetch(self, version: Optional[int] = None) -> FetchStats:
        """Pull `version`'s chunk BYTES into this process's object
        store without assembling any array — the subscriber-prefetch
        path: WeightSync starts this the moment a version commits,
        while the engine is still decoding the previous one, so the
        later ``fetch(like=)`` finds every chunk local and the swap
        critical section is assembly+apply only.

        Skips chunks already present (an unchanged delta leaf whose
        chunks an earlier fetch pulled costs nothing). Implies
        ``cache_chunks``. Returns the transfer accounting."""
        self._cache_chunks = True
        stats = FetchStats()
        t0 = time.perf_counter()
        manifest = self._worker.conductor.call(
            "weights_get_manifest", self.name, version, timeout=30.0)
        if manifest is None:
            raise KeyError(
                f"no committed version "
                f"{'(latest)' if version is None else version} "
                f"of weights {self.name!r} in the registry")
        stats.version = int(manifest["version"])
        stats.delta = bool(manifest.get("delta"))
        stats.base_version = manifest.get("base_version")
        stats.changed_leaves = manifest.get("changed_leaves")
        # seed from whatever was prefetched before (oid-keyed, so a
        # delta version reuses every unchanged chunk of the PREVIOUS
        # version for free), then keep only this manifest's chunks
        prev = self._prefetched
        fetcher = _ChunkFetcher(self._worker, stats,
                                seed_cache=prev[1] if prev else None)
        needed = set()
        for leaf in manifest["leaves"]:
            for shard in leaf["shards"]:
                needed.add(shard["object_id"])
                fetcher(shard)
        self._store_prefetched(
            stats.version, {oid: arr for oid, arr
                            in fetcher.cache.items() if oid in needed})
        stats.elapsed_s = time.perf_counter() - t0
        if stats.fetched_bytes:
            try:
                self._worker.conductor.notify("report_weight_event", {
                    "kind": "prefetch", "name": self.name,
                    "version": stats.version,
                    "fetched_bytes": stats.fetched_bytes,
                    "chunks": stats.chunks_fetched})
            except Exception:  # noqa: BLE001 — telemetry only
                pass
        return stats

    # -------------------------------------------------------------- fetch

    def fetch(self, *, version: Optional[int] = None,
              like: Any = None) -> Any:
        """Materialize `version` (latest when None).

        ``like=template``: template-leaf shardings drive the assembly —
        each device reads only its own slice, fetching only the chunks
        that slice intersects (reshard-on-fetch). ``like=None`` returns
        plain numpy leaves via the producer's treedef (debug/CLI path —
        this one DOES assemble full arrays; serving should always pass
        a template)."""
        stats = FetchStats()
        t0 = time.perf_counter()
        manifest = self._worker.conductor.call(
            "weights_get_manifest", self.name, version, timeout=30.0)
        if manifest is None:
            raise KeyError(
                f"no committed version {'(latest)' if version is None else version} "
                f"of weights {self.name!r} in the registry")
        stats.version = int(manifest["version"])
        stats.delta = bool(manifest.get("delta"))
        stats.base_version = manifest.get("base_version")
        stats.changed_leaves = manifest.get("changed_leaves")
        # seed from the prefetched chunks (oid-keyed, so both "this
        # version was prefetched" and "a delta reuses the previous
        # version's unchanged chunks" come for free); their first use
        # accounts as a local read
        prev = self._prefetched
        fetcher = _ChunkFetcher(self._worker, stats,
                                seed_cache=prev[1] if prev else None)
        machine = chunks.local_machine_id()
        readers: List[_AccountingReader] = []
        for i, leaf in enumerate(manifest["leaves"]):
            shape = tuple(leaf["shape"])
            dtype = np.dtype(leaf["dtype"])
            full = int(np.prod(shape)) * dtype.itemsize if shape \
                else dtype.itemsize
            stats.leaf_read_bytes.append(
                {"leaf": i, "max_read_bytes": 0, "full_nbytes": full})
            # same-host placement hint: order this host's chunks first —
            # the reader's coverage mask then skips loading any remote
            # replica of a slice a colocated (shm) chunk already filled
            shards = sorted(leaf["shards"],
                            key=lambda s: s.get("machine", "") != machine)
            readers.append(_AccountingReader(
                shape, dtype, shards, fetcher, stats, i))
        if like is None:
            if manifest.get("treedef") is None:
                raise ValueError(
                    f"version {stats.version} of {self.name!r} carries "
                    "no treedef (host-0 fragment missing it); pass "
                    "like= to fetch")
            treedef = pickle.loads(manifest["treedef"])
            leaves = [r.read(tuple(slice(0, d) for d in r.shape))
                      for r in readers]
            import jax

            out = jax.tree.unflatten(treedef, leaves)
        else:
            import jax

            _, treedef = jax.tree.flatten(like)
            if treedef.num_leaves != len(readers):
                raise ValueError(
                    f"template has {treedef.num_leaves} leaves but "
                    f"version {stats.version} of {self.name!r} was "
                    f"published with {len(readers)}")
            out = materialize_like(readers, treedef, like)
        if self._cache_chunks:
            # carry the pulled chunks forward (pruned to THIS
            # manifest's object ids): the next delta fetch reuses
            # every unchanged chunk without another transfer
            manifest_oids = {s["object_id"]
                             for leaf in manifest["leaves"]
                             for s in leaf["shards"]}
            self._store_prefetched(
                stats.version, {oid: arr for oid, arr
                                in fetcher.cache.items()
                                if oid in manifest_oids})
        stats.elapsed_s = time.perf_counter() - t0
        self.last_stats = stats
        m = weight_metrics()
        m["fetches"].inc(1, tags={"name": self.name})
        if stats.fetched_bytes:
            m["fetched_bytes"].inc(stats.fetched_bytes,
                                   tags={"name": self.name})
        try:
            self._worker.conductor.notify("report_weight_event", {
                "kind": "fetch", "name": self.name,
                "version": stats.version,
                "fetched_bytes": stats.fetched_bytes,
                "chunks": stats.chunks_fetched + stats.chunks_local})
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        return out

    def close(self) -> None:
        with self._pf_lock:
            self._prefetched = None
        try:
            self._worker.unsubscribe_channel("weights",
                                             self._on_weights_msg)
        except Exception:  # noqa: BLE001 — worker already torn down
            pass
