"""Shared internals of the weight fabric."""
from __future__ import annotations

from ray_tpu.util.runtime import require_worker  # noqa: F401
