"""Shared internals of the weight fabric."""
from __future__ import annotations


def require_worker(what: str):
    """The connected global worker, or a clear error naming the weight-
    fabric operation that needed it."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        raise RuntimeError(
            f"ray_tpu.init() must be called before {what}")
    return w
