"""Producer side of the live weight fabric.

Each host of a training gang publishes ONLY its local shards — there is
never a single-host gather, for any leaf, anywhere in the fabric. Every
addressable replica-0 shard of every jax.Array leaf goes into THIS
process's object store as its own chunk (the shm path serves same-host
readers zero-copy; remote readers stream it through the existing 64MB
chunked fetch), and a metadata-only fragment rides one RPC to the
conductor's version registry. The registry commits the version
atomically when the LAST host's fragment lands — subscribers can never
observe a torn publish.

Ownership model consequence (deliberate, matching the object plane): the
chunks live exactly as long as the publishing process. Publish from a
process that outlives consumption (the spmd driver, a parameter-server
actor, a long-lived gang) — not from a worker that exits right after.

GC: the registry's keep-last-K (and partial-publish reaping) notifies
producers on the `weights` pubsub channel; the publisher drops its
ObjectRefs for dropped versions and the refcount layer frees the store
entries.
"""
from __future__ import annotations

import hashlib
import pickle
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.train.async_checkpoint import _leaf_snapshots
from ray_tpu.util import chunks

from ._common import require_worker
from .metrics import weight_metrics


def _worker():
    return require_worker("publishing weights")


def _hash_snapshot(meta: Dict[str, Any],
                   shards: List[Tuple[tuple, Any]]) -> str:
    """Content hash of one leaf's host-local snapshot (blake2b over
    shard index + bytes, plus shape/dtype so a reshaped same-bytes leaf
    never reads as unchanged). Hashes the array buffer directly — no
    bytes copy."""
    h = hashlib.blake2b(digest_size=16)
    h.update(repr((meta["shape"], meta["dtype"])).encode())
    for index, host_arr in shards:
        h.update(repr(index).encode())
        h.update(chunks.ensure_chunkable(host_arr).data)
    return h.hexdigest()


def leaf_content_hashes(tree: Any) -> List[str]:
    """Per-leaf content hash of THIS host's local shards — the
    delta-publication change detector: two publishes of a leaf hash
    equal iff this host's share of it is bit-identical."""
    import jax

    leaves, _ = jax.tree.flatten(tree)
    return [_hash_snapshot(*_leaf_snapshots(leaf)) for leaf in leaves]


class WeightPublisher:
    """Publishes versions of one named weight set from this process.

    host_rank/num_hosts default to the jax distributed identity, so a
    multi-host gang where every host constructs a publisher and calls
    :meth:`publish` with the same step commits one joint version made of
    every host's local shards.
    """

    def __init__(self, name: str = "default", *,
                 host_rank: Optional[int] = None,
                 num_hosts: Optional[int] = None):
        import jax

        self.name = name
        self.host_rank = (jax.process_index() if host_rank is None
                          else int(host_rank))
        self.num_hosts = (jax.process_count() if num_hosts is None
                          else int(num_hosts))
        self._worker = _worker()
        # version -> chunk refs: holding the refs IS what keeps the
        # chunks alive (refcount ownership); dropped on gc/reap notice.
        # With delta publication a chunk can be referenced by manifests
        # NEWER than the version it was published under — gc notices
        # name explicit object ids and the registry withholds ids still
        # referenced by kept manifests, so refs held under an old
        # version key keep pinning exactly the chunks that are live.
        self._refs: Dict[int, List[Any]] = {}
        # delta base: the per-leaf content hashes of this host's share
        # of the LAST publish that committed from this publisher
        self._last_version: Optional[int] = None
        self._last_hashes: Optional[List[str]] = None
        self._lock = threading.Lock()
        self._worker.subscribe_channel("weights", self._on_weights_msg)

    # ------------------------------------------------------------- publish

    def publish(self, tree: Any, *, step: Optional[int] = None,
                version: Optional[int] = None, run_id: str = "",
                delta: bool = False) -> int:
        """Publish this host's local shards of `tree` as `version`
        (defaults to `step`, else registry-latest + 1 — multi-host gangs
        must pass an explicit step so every host names the same
        version). Returns the version id; the version is fetchable once
        every host committed.

        ``delta=True`` ships only the leaves whose content hash changed
        since this publisher's previous publish (the base version): the
        fragment names the base and the unchanged leaves inherit the
        base manifest's chunk entries at commit, so per-step refresh
        pays for the optimizer's actual movement, not the whole model.
        Falls back to a FULL publication when there is no base to delta
        against (first publish, or the base was GC'd from the
        registry)."""
        import jax

        t0 = time.perf_counter()
        if version is None:
            if step is None:
                if self.num_hosts > 1:
                    # registry-assigned numbering is a per-host race in
                    # a gang: two hosts in different rounds could name
                    # the same version and the registry would commit a
                    # manifest MIXING rounds across hosts
                    raise ValueError(
                        "multi-host publishes need an explicit step= "
                        "(every host must name the same version)")
                version = self._next_version()
            else:
                version = step
        version = int(version)
        # best-effort pre-check: a restarted attempt replaying
        # already-published steps must not pay a full local-shard copy
        # into the store only to have the registry reject it (the
        # registry's own check remains authoritative under races)
        try:
            exists = self._worker.conductor.call(
                "weights_has_version", self.name, version, timeout=10.0)
        except Exception:  # noqa: BLE001 — probe only
            exists = False
        if exists:
            raise ValueError(
                f"weight publish rejected: version {version} of "
                f"{self.name!r} is already committed")
        leaves, treedef = jax.tree.flatten(tree)
        # snapshot once (device->host copy of replica-0 shards); hash
        # ONLY on delta publishes — a delta-less workflow must not pay
        # a full-model hash per publish. The first delta publish
        # therefore has no base (it goes out full) and seeds the chain.
        snaps = [_leaf_snapshots(leaf) for leaf in leaves]
        hashes = [_hash_snapshot(meta, shards)
                  for meta, shards in snaps] if delta else None
        base_version: Optional[int] = None
        base_hashes: Optional[List[str]] = None
        if delta and self._last_version is not None \
                and self._last_hashes is not None \
                and len(self._last_hashes) == len(leaves):
            base_version = self._last_version
            base_hashes = self._last_hashes
            try:
                if not self._worker.conductor.call(
                        "weights_has_version", self.name, base_version,
                        timeout=10.0):
                    # full fallback: the base aged out of the registry
                    # (keep-last-K GC or operator gc) — nothing to
                    # inherit unchanged leaves from
                    base_version = base_hashes = None
            except Exception:  # noqa: BLE001 — probe only; the commit
                pass           # re-checks under its own lock
        frag_leaves: Dict[str, Any] = {}
        refs: List[Any] = []
        w = self._worker
        for i, (meta, shards) in enumerate(snaps):
            if base_hashes is not None and hashes[i] == base_hashes[i]:
                # unchanged since the base: ship metadata only; the
                # registry inherits the base manifest's chunk entries
                # for this host at commit
                frag_leaves[str(i)] = {**meta, "hash": hashes[i],
                                       "from_base": True, "shards": []}
                continue
            entries = []
            for index, host_arr in shards:
                # shared chunked-transfer path (util.chunks): the put
                # side of the fabric's 64MB-chunked no-gather transfer,
                # incl. the ascontiguousarray 0-d promotion guard
                ref, entry = chunks.put_chunk(w, host_arr)
                refs.append(ref)
                entries.append(dict(entry,
                                    index=[list(t) for t in index]))
            frag_leaves[str(i)] = {
                **meta, "hash": hashes[i] if hashes else None,
                "shards": entries}
        fragment: Dict[str, Any] = {"leaves": frag_leaves,
                                    "n_leaves": len(leaves)}
        if base_version is not None:
            fragment["base_version"] = base_version
        if self.host_rank == 0:
            fragment["treedef"] = pickle.dumps(treedef, protocol=5)
        with self._lock:
            self._refs.setdefault(version, []).extend(refs)
        try:
            res = w.conductor.call(
                "weights_publish_fragment", self.name, version,
                self.host_rank, self.num_hosts, fragment, run_id, step,
                timeout=60.0)
        except Exception:
            # Transport failure is ambiguous: the fragment may have
            # landed before the timeout. Probe the registry — if the
            # version is pending or committed there, the chunks are (or
            # will be) referenced and gc/reap notices will release
            # them; only a fragment that verifiably never landed has
            # refs nothing will ever reap, which must be dropped here
            # or every failed publish leaks a full local-shard copy.
            if not self._fragment_landed(version):
                self._drop_call_refs(version, refs)
            raise
        if res.get("error"):
            self._drop_call_refs(version, refs)
            if "delta base" in res["error"]:
                # the base was GC'd between our probe and the commit
                # (registry-authoritative check): full fallback — and
                # the hashes already computed for THIS tree seed the
                # chain, so the next delta diffs against the fallback
                # instead of also going out full
                self._last_version = self._last_hashes = None
                v = self.publish(tree, step=step, version=version,
                                 run_id=run_id, delta=False)
                self._last_version = v
                self._last_hashes = hashes
                return v
            raise ValueError(f"weight publish rejected: {res['error']}")
        if hashes is not None:
            self._last_version = version
            self._last_hashes = hashes
        m = weight_metrics()
        m["publish_ms"].observe((time.perf_counter() - t0) * 1e3,
                                tags={"name": self.name})
        m["published"].inc(1, tags={"name": self.name})
        return version

    def _fragment_landed(self, version: int) -> bool:
        """Did the registry record `version` (pending or committed)?
        Unreachable registry reads as True: keeping refs until close()
        (a bounded leak) beats freeing chunks a committed manifest may
        reference."""
        try:
            listing = self._worker.conductor.call("get_weight_versions",
                                                  timeout=10.0)
        except Exception:  # noqa: BLE001 — conductor unreachable
            return True
        rec = (listing.get("names") or {}).get(self.name)
        if rec and any(v["version"] == version for v in rec["versions"]):
            return True
        return any(p.get("name") == self.name
                   and p.get("version") == version
                   for p in listing.get("pending") or ())

    def _drop_call_refs(self, version: int, refs: List[Any]) -> None:
        """Drop ONLY this call's refs: a duplicate-version publish must
        not free the chunks of the already-committed version sharing
        the number."""
        with self._lock:
            held = self._refs.get(version)
            if held is None:
                return
            mine = {r.id for r in refs}
            held[:] = [r for r in held if r.id not in mine]
            if not held:
                del self._refs[version]

    def _next_version(self) -> int:
        listing = self._worker.conductor.call("get_weight_versions",
                                              timeout=30.0)
        rec = (listing.get("names") or {}).get(self.name)
        return (int(rec["latest"]) + 1) if rec else 1

    # ----------------------------------------------------------------- gc

    def _on_weights_msg(self, msg: Any) -> None:
        """Registry notices: drop refs for GC'd/reaped chunks so the
        refcount layer frees this process's store entries. Notices name
        EXPLICIT object ids — dropping by version number alone would
        also free a NEW publish in flight under a reused version number
        (the gang-resize supersede case)."""
        if not isinstance(msg, dict) or msg.get("name") != self.name:
            return
        if msg.get("kind") not in ("gc", "reaped"):
            return
        with self._lock:
            if "object_ids" in msg:
                # explicit-id protocol — an EMPTY list is meaningful
                # (every chunk of the dropped version is still
                # referenced by a kept delta manifest: free nothing)
                ids = set(msg["object_ids"] or ())
                for v in list(self._refs):
                    held = self._refs[v]
                    held[:] = [r for r in held if r.id not in ids]
                    if not held:
                        del self._refs[v]
            else:
                # id-less notice (older conductor): version-scoped drop
                for v in msg.get("versions") or ():
                    self._refs.pop(int(v), None)

    def held_versions(self) -> List[int]:
        with self._lock:
            return sorted(self._refs)

    def close(self) -> None:
        """Drop every held version's chunks and the pubsub callback."""
        try:
            self._worker.unsubscribe_channel("weights",
                                             self._on_weights_msg)
        except Exception:  # noqa: BLE001 — worker already torn down
            pass
        with self._lock:
            self._refs.clear()


# Module-level publishers, one per name: refs must outlive publish() —
# they ARE the chunks' lifetime — so `weights.publish(...)` keeps its
# publisher (and the refs it holds) alive in the process.
_publishers: Dict[str, WeightPublisher] = {}
_publishers_lock = threading.Lock()


def publish(tree: Any, *, name: str = "default",
            step: Optional[int] = None, version: Optional[int] = None,
            run_id: str = "", delta: bool = False) -> int:
    """Publish from a per-name process-cached :class:`WeightPublisher`
    (`ray_tpu.train.report(..., publish_weights=...)` lands here).
    ``delta=True`` ships only the leaves that changed since this
    process's previous publish of `name` (full fallback when there is
    no usable base) — the caching is what gives consecutive report()
    publishes a base to diff against."""
    cur = _worker()
    with _publishers_lock:
        pub = _publishers.get(name)
        if pub is None or pub._worker is not cur:
            # a publisher from a previous init/shutdown cycle holds a
            # dead worker (and chunks that died with it) — replace it
            pub = _publishers[name] = WeightPublisher(name)
    return pub.publish(tree, step=step, version=version, run_id=run_id,
                       delta=delta)


def _reset_publishers() -> None:
    """Test/shutdown hook: drop cached publishers (and their chunks)."""
    with _publishers_lock:
        for pub in _publishers.values():
            pub.close()
        _publishers.clear()
