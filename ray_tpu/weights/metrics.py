"""Prometheus surface of the weight fabric — lazily created so
importing ray_tpu.weights never spawns a metrics pusher (the pattern the
conductor uses for its resilience counters). All three ride the
util.metrics conductor-push pipeline into /api/metrics and
`ray_tpu metrics`:

- ray_tpu_weights_publish_ms      publish latency (shards put + commit)
- ray_tpu_weights_fetched_bytes_total   chunk bytes pulled by consumers
- ray_tpu_weights_staleness_versions    per-replica serving-version age
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

# Rebound ONCE, to a fully-built dict: the unlocked fast path can only
# ever observe None or the complete registry, never a partial one.
_metrics: Optional[Dict[str, Any]] = None
_lock = threading.Lock()

_PUBLISH_BOUNDS_MS = [1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0]


def weight_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge, Histogram

            _metrics = dict(
                publish_ms=Histogram(
                    "ray_tpu_weights_publish_ms",
                    "weight publish latency: local shards into the "
                    "object store + registry commit",
                    boundaries=_PUBLISH_BOUNDS_MS, tag_keys=("name",)),
                published=Counter(
                    "ray_tpu_weights_published_total",
                    "weight versions published", tag_keys=("name",)),
                fetched_bytes=Counter(
                    "ray_tpu_weights_fetched_bytes_total",
                    "weight chunk bytes fetched by this process",
                    tag_keys=("name",)),
                fetches=Counter(
                    "ray_tpu_weights_fetches_total",
                    "weight version fetches completed by this process",
                    tag_keys=("name",)),
                staleness=Gauge(
                    "ray_tpu_weights_staleness_versions",
                    "latest published version minus the version this "
                    "consumer is serving (0 = fresh)",
                    tag_keys=("name", "consumer")))
    return _metrics
