"""WeightSync: keep a serving engine on the latest published weights.

A background thread (pubsub-nudged, poll-backed) watches the registry;
on a new version it reshards-on-fetch under the consumer's own template
shardings and queues a hot swap that the continuous-batching engine
applies BETWEEN decode ticks — in-flight requests keep their KV caches
and complete, nothing restarts, nothing drops. The per-replica staleness
gauge (latest published version minus serving version) updates on every
cycle, and each applied swap lands a marker in the conductor's weight
event log (merged timeline)."""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from .metrics import weight_metrics
from .subscriber import WeightSubscriber

logger = logging.getLogger("ray_tpu.weights")


class WeightSync:
    """Drives one engine (anything with ``update_params(params, version)``
    and a ``params_version`` attribute — models.ContinuousBatchingEngine)
    from one named weight set."""

    def __init__(self, engine: Any, name: str = "default", *,
                 template: Any = None, consumer: str = "",
                 poll_interval_s: float = 0.5,
                 subscriber: Optional[WeightSubscriber] = None,
                 prefetch: bool = False):
        self.engine = engine
        self.name = name
        # the reshard target: defaults to the engine's current params
        # (their shardings/dtypes ARE the serving layout)
        self.template = template if template is not None else engine.params
        self.consumer = consumer or f"pid-{os.getpid()}"
        self.poll_interval_s = poll_interval_s
        self._sub = subscriber or WeightSubscriber(
            name, cache_chunks=prefetch)
        self._stop = threading.Event()
        self._swapped = threading.Condition()
        self.swap_count = 0
        self.last_error: Optional[str] = None
        # staleness high-water mark over this sync's lifetime (poll-
        # cycle sampled) — the online loop's <= 1 invariant reads it
        self.max_staleness: Optional[int] = None
        # False the moment a registry probe fails; True again on the
        # next successful cycle. status() exposes it so a caller can
        # tell "fresh" apart from "the registry stopped answering and
        # `latest` is whatever we last learned".
        self.registry_reachable = True
        # subscriber prefetch: a pubsub "published" notice immediately
        # pulls the new version's chunk bytes into this process's store
        # on a side thread, while the engine still decodes the old
        # version — by the time the sync loop assembles + swaps, every
        # chunk is local and the critical section is apply-only
        self.prefetch = prefetch
        self.prefetch_bytes = 0
        self.prefetched_version: Optional[int] = None
        self._prefetch_lock = threading.Lock()
        if prefetch:
            self._sub._worker.subscribe_channel("weights",
                                                self._on_published)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"weight-sync-{name}")
        self._thread.start()

    # ----------------------------------------------------------- prefetch

    def _on_published(self, msg: Any) -> None:
        if not isinstance(msg, dict) or msg.get("name") != self.name \
                or msg.get("kind") != "published":
            return
        version = msg.get("version")
        t = threading.Thread(target=self._prefetch_one, args=(version,),
                             daemon=True,
                             name=f"weight-prefetch-{self.name}")
        t.start()

    def _prefetch_one(self, version) -> None:
        with self._prefetch_lock:  # one transfer at a time; a burst of
            # publishes degrades to prefetching the newest last, which
            # is the one the sync loop will swap to
            if self._stop.is_set():
                return
            try:
                st = self._sub.prefetch(version=version)
            except Exception:  # noqa: BLE001 — version GC'd/reaped
                return         # between notice and pull; fetch retries
            self.prefetch_bytes += st.fetched_bytes
            self.prefetched_version = st.version

    # ------------------------------------------------------------- status

    def status(self) -> Dict[str, Any]:
        latest = None
        try:
            latest = self._sub.latest_version()
            self.registry_reachable = True
        except Exception as e:  # noqa: BLE001 — conductor unreachable
            self.last_error = str(e)
            self.registry_reachable = False
        serving = getattr(self.engine, "params_version", None)
        # staleness is unknowable (None), not huge, until the engine is
        # actually serving a fabric version — versions are step numbers,
        # so "latest - 0" would trip every staleness alert at boot.
        # Equally unknowable with the registry unreachable: `latest` is
        # then stale knowledge, not a freshness certificate.
        staleness = None
        if latest is not None and serving is not None \
                and self.registry_reachable:
            staleness = latest - serving
            self.max_staleness = staleness if self.max_staleness is None \
                else max(self.max_staleness, staleness)
        st = self._sub.last_stats
        return {"name": self.name, "consumer": self.consumer,
                "serving_version": serving, "latest_version": latest,
                "registry_reachable": self.registry_reachable,
                "staleness_versions": staleness,
                "max_staleness_versions": self.max_staleness,
                "swap_count": self.swap_count,
                "fetched_bytes": st.fetched_bytes if st else 0,
                "rpc_bytes": st.rpc_bytes if st else 0,
                "shm_bytes": st.shm_bytes if st else 0,
                "max_read_bytes": st.max_read_bytes if st else 0,
                "leaf_read_bytes": list(st.leaf_read_bytes) if st else [],
                "prefetch_bytes": self.prefetch_bytes,
                "prefetched_version": self.prefetched_version,
                "last_error": self.last_error}

    def wait_for_swap(self, min_version: int, timeout: float = 30.0
                      ) -> int:
        """Block until the ENGINE serves a version >= min_version (the
        swap has been applied between ticks, not merely queued)."""
        deadline = time.monotonic() + timeout
        with self._swapped:
            while True:
                v = getattr(self.engine, "params_version", None)
                if v is not None and v >= min_version:
                    return v
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"engine still serving {v} (< {min_version}) "
                        f"after {timeout}s; last_error={self.last_error}")
                self._swapped.wait(min(remaining, 0.2))

    # --------------------------------------------------------------- loop

    def _gauge(self, latest: Optional[int]) -> None:
        serving = getattr(self.engine, "params_version", None)
        if latest is None or serving is None \
                or not self.registry_reachable:
            # unknown staleness: emit nothing — neither a bogus delta
            # nor a reassuring 0 while the registry is unreachable (the
            # gauge keeps its LAST known value; the reachability flag is
            # what tells the operator it may be stale)
            return
        staleness = latest - serving
        self.max_staleness = staleness if self.max_staleness is None \
            else max(self.max_staleness, staleness)
        weight_metrics()["staleness"].set(
            float(staleness),
            tags={"name": self.name, "consumer": self.consumer})

    def _engine_stopped(self) -> bool:
        stopped = getattr(self.engine, "_stopped", None)
        return stopped is not None and stopped.is_set()

    def _loop(self) -> None:
        failed_cycles = 0
        while not self._stop.is_set():
            if self._engine_stopped():
                # nothing left to swap into — a queued swap would never
                # apply and every cycle would refetch the full model
                self.last_error = "engine stopped; weight sync idle"
                return
            try:
                latest = self._sub.latest_version()
                self.registry_reachable = True
                serving = getattr(self.engine, "params_version", None)
                # follow whatever the registry calls latest (committed
                # most recently) rather than `>`: a gang restarted from
                # an older checkpoint republishes LOWER version numbers,
                # and those are the live weights
                if latest is not None and latest != serving:
                    if self.prefetch:
                        # barrier on the pubsub-kicked transfer (or do
                        # the pull now — idempotent: chunks already
                        # local cost nothing): after this, fetch() is
                        # pure assembly
                        self._prefetch_one(latest)
                    params = self._sub.fetch(version=latest,
                                             like=self.template)
                    applied = self.engine.update_params(params,
                                                        version=latest)
                    if applied is not None and \
                            not applied.wait(timeout=60.0):
                        # swap queued but not applied (wedged or stopped
                        # decode loop): surface through the except path
                        # — status/staleness must keep telling the
                        # truth, not record the version as served
                        raise RuntimeError(
                            f"swap to v{latest} not applied within 60s "
                            "(decode loop wedged or engine stopped)")
                    # re-point the reshard template at the weights now
                    # being served (same shapes/dtypes/shardings):
                    # keeping the ORIGINAL params alive as the template
                    # would pin a dead full copy of the model forever
                    self.template = params
                    self.swap_count += 1
                    st = self._sub.last_stats
                    try:
                        self._sub._worker.conductor.notify(
                            "report_weight_event", {
                                "kind": "swap", "name": self.name,
                                "version": latest,
                                "consumer": self.consumer,
                                "fetched_bytes":
                                    st.fetched_bytes if st else 0})
                    except Exception:  # noqa: BLE001 — telemetry only
                        pass
                    with self._swapped:
                        self._swapped.notify_all()
                self._gauge(latest)
                failed_cycles = 0
                self.last_error = None  # any healthy cycle clears it —
                # status() must not report a long-resolved blip forever
            except Exception as e:  # noqa: BLE001 — keep serving on a
                # failed cycle (registry mid-restart, version GC'd
                # between list and fetch); next cycle retries
                failed_cycles += 1
                self.last_error = f"{type(e).__name__}: {e}"
                self.registry_reachable = False
                logger.debug("weight sync cycle failed: %s", e)
            # pubsub publish notices wake the subscriber cv; this wait
            # piggybacks on it so swaps start promptly without a hot
            # loop. Failed cycles back off — a repeatedly-failing fetch
            # of a large model must not retry at poll cadence.
            wait_s = self.poll_interval_s if not failed_cycles else \
                min(self.poll_interval_s * (2 ** failed_cycles), 30.0)
            with self._sub._cv:
                self._sub._cv.wait(wait_s)

    def stop(self) -> None:
        self._stop.set()
        if self.prefetch:
            try:
                self._sub._worker.unsubscribe_channel(
                    "weights", self._on_published)
            except Exception:  # noqa: BLE001 — worker already torn down
                pass
        with self._sub._cv:
            self._sub._cv.notify_all()
        self._thread.join(timeout=10.0)
        self._sub.close()
