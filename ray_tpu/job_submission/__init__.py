"""Job submission SDK — analog of the reference's
python/ray/job_submission/ (JobSubmissionClient, JobStatus) +
dashboard/modules/job JobManager. Entrypoint drivers run as head-node
subprocesses with RAY_TPU_ADDRESS injected (reference: drivers run on the
head/worker via the JobManager actor)."""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = frozenset({SUCCEEDED, FAILED, STOPPED})


class JobSubmissionClient:
    """``JobSubmissionClient("host:port")`` or, inside an inited driver,
    ``JobSubmissionClient()`` to use the current cluster."""

    def __init__(self, address: Optional[str] = None):
        from ray_tpu._private.rpc import RpcClient

        if address is None:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker
            if w is None:
                raise RuntimeError(
                    "no address given and ray_tpu.init() not called")
            self._client = w.conductor
        else:
            host, _, port = address.rpartition(":")
            self._client = RpcClient((host or "127.0.0.1", int(port)))

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        env = dict((runtime_env or {}).get("env_vars") or {})
        working_dir = (runtime_env or {}).get("working_dir")
        return self._client.call(
            "submit_job", entrypoint, env, submission_id, working_dir,
            metadata, timeout=30.0)

    def get_job_status(self, job_id: str) -> str:
        info = self._client.call("get_job", job_id, timeout=10.0)
        if info is None:
            raise KeyError(f"no job {job_id}")
        return info["status"]

    def get_job_info(self, job_id: str) -> Dict[str, Any]:
        info = self._client.call("get_job", job_id, timeout=10.0)
        if info is None:
            raise KeyError(f"no job {job_id}")
        return info

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._client.call("list_jobs", timeout=10.0)

    def get_job_logs(self, job_id: str) -> str:
        return self._client.call("get_job_logs", job_id, timeout=30.0)

    def stop_job(self, job_id: str) -> bool:
        return self._client.call("stop_job", job_id, timeout=10.0)

    def wait_until_finished(self, job_id: str, timeout: float = 300.0,
                            poll_s: float = 0.2) -> str:
        deadline = time.monotonic() + timeout
        while True:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"job {job_id} still {status} after {timeout}s")
            time.sleep(poll_s)
