"""Web dashboard: cluster state over HTTP + a single-file SPA.

Replaces the reference's `dashboard/` subsystem (aiohttp head + per-node
agents + React SPA, dashboard/dashboard.py, dashboard/client/) with one
aiohttp server beside the conductor. There are no per-node dashboard
agents to aggregate: the conductor is already the single authority for
nodes/workers/actors/jobs, and per-worker object stats are one RPC away.

Routes:
  /                      the SPA (ray_tpu/dashboard/index.html)
  /api/summary           cluster overview (nodes + resources + counts)
  /api/nodes|workers|actors|placement_groups|jobs
  /api/objects           per-process object store stats (fan-out)
  /api/tasks             task-name summary table
  /api/timeline          chrome-trace JSON of task events
  /api/metrics           Prometheus exposition (text)
  /api/serve             Serve apps/deployments/proxies (controller's
                         KV-mirrored status)
  /api/resilience        recovery subsystem: quarantined/draining hosts,
                         failure scores, restart/preemption counters
  /api/weights           live weight fabric: committed/pending versions
                         per weight-set name (ray_tpu.weights registry)
  /api/kvcache           paged KV prefix cache: per-engine stats +
                         totals (hit rates, pool utilization) and
                         recent prefix-hit/evict events
  /api/speculation       speculative decoding: per-engine draft
                         proposal/acceptance counters, tokens-per-
                         verify, int8-KV flag, and the kvcache lane's
                         spec_accept/spec_reject marker slice
                         (models/engine.py)
  /api/pipeline          MPMD pipelines: stage registry + per-stage
                         bubble fraction / channel bytes and recent
                         pipeline events (ray_tpu.mpmd)
  /api/online            online learning loop: sampler rollout +
                         staleness stats, buffer occupancy, learner
                         ingest, recent rollout/publish/swap/ingest
                         events (ray_tpu.online)
  /api/disagg            disaggregated prefill/decode serving: prefill
                         reuse + published KV, decode transfer
                         accounting (shm vs rpc), router shed/queue
                         depth, recent kv_publish/kv_transfer/shed
                         events (serve/disagg.py)
  /api/kvplane           global KV plane: per-replica host arenas
                         (tier-2 entries/bytes, spills, re-adopted
                         tokens), tier-3 publish/adopt traffic, prefix
                         directory summary + routing outcomes, recent
                         spill/tier2_hit/tier3_publish/tier3_adopt/
                         directory_hit events (serve/kvplane.py)
  /api/autoscale         serving autoscaler: per-loop tier targets,
                         scale-up/down decision counts, drain
                         outcomes, replica-seconds, recent scale_up/
                         drain/scale_down events (serve/autoscale.py;
                         the NODE-level autoscaler stays at
                         /api/autoscaler)
  /api/servefault        serving-plane fault tolerance: per-router
                         failovers by phase + sheds by cause, healer
                         deaths/replacements/breaker state, and the
                         resilience lane's failover/replace/
                         breaker_trip event slice (serve/disagg.py +
                         serve/autoscale.py self-healing)
  /api/lora              multi-tenant LoRA serving: adapter-pool
                         paging (hits/misses/evictions/swaps,
                         residents), per-tenant request counters,
                         recent page_in/evict/swap events
                         (serve/lora.py)
  /api/gateway           HTTP front door: per-replica request counters
                         by priority class and status code, recent
                         TTFT per class, QoS admissions, batch-slot
                         preemptions, recent accept/first_byte/
                         preempt/rate_limit/disconnect events
                         (serve/gateway.py + serve/qos.py)
  /api/oracle            step-time oracle: roofline predictions per
                         layout (device/ici/dcn breakdown),
                         predicted-vs-measured validations (residuals,
                         fitted calibration), recent prediction/
                         validation events (observability.roofline)
  /api/requesttrace      per-request flight recorder: completed/kept/
                         dropped totals, outcome tally, p99 phase
                         attribution (tail owner), slowest requests
                         with per-phase latency breakdowns, recent
                         kept-trace events (observability.requests)
  /api/actors/{id}       actor drill-down (record, worker, recent task
                         events, store stats)
"""
from __future__ import annotations

import asyncio
import os
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private.rpc import ClientPool, ReconnectingClient

DEFAULT_DASHBOARD_PORT = 8265


class _ClusterData:
    """Blocking conductor/worker queries (called via run_in_executor)."""

    def __init__(self, conductor_address: Tuple[str, int]):
        self.conductor = ReconnectingClient(conductor_address)
        self.pool = ClientPool()

    def summary(self) -> Dict[str, Any]:
        c = self.conductor
        return {
            "timestamp": time.time(),
            "address": list(self.conductor.address),
            "nodes": c.call("nodes", timeout=5.0),
            "resources_total": c.call("cluster_resources", timeout=5.0),
            "resources_available": c.call("available_resources", timeout=5.0),
            "num_workers": len(c.call("list_workers", timeout=5.0)),
            "num_actors": len(c.call("list_actors", timeout=5.0)),
        }

    def simple(self, method: str) -> Any:
        return self.conductor.call(method, timeout=10.0)

    def simple_args(self, method: str, *args) -> Any:
        return self.conductor.call(method, *args, timeout=10.0)

    def objects(self) -> List[Dict[str, Any]]:
        out = []
        for rec in self.conductor.call("list_workers", timeout=5.0):
            addr = rec.get("address")
            if not addr or rec.get("state") == "DEAD":
                continue
            try:
                out.append(self.pool.get(tuple(addr)).call("store_stats",
                                                           timeout=3.0))
            except Exception:  # noqa: BLE001 — worker mid-restart
                pass
        return out

    def tasks_summary(self) -> List[Dict[str, Any]]:
        events = self.conductor.call("get_task_events", 10_000, timeout=10.0)
        groups: Dict[str, Dict[str, Any]] = defaultdict(
            lambda: {"count": 0, "failed": 0, "total_s": 0.0})
        for ev in events:
            g = groups[ev["name"]]
            g["count"] += 1
            g["failed"] += 1 if ev.get("status") == "FAILED" else 0
            g["total_s"] += max(0.0, ev["end"] - ev["start"])
        return [dict(name=k, mean_s=v["total_s"] / max(1, v["count"]), **v)
                for k, v in sorted(groups.items())]

    def timeline(self) -> List[Dict[str, Any]]:
        from ray_tpu.observability.timeline import task_trace_events

        events = self.conductor.call("get_task_events", 10_000, timeout=10.0)
        return task_trace_events(events)

    def metrics_text(self) -> str:
        from ray_tpu.util.state import _render_prometheus

        return _render_prometheus(self.conductor.call("get_metrics",
                                                      timeout=5.0))

    def train_progress(self) -> Dict[str, Any]:
        """Flight-recorder gang telemetry (per-rank step stats, skew,
        stragglers) aggregated by the conductor. Int rank keys are fine:
        json_response's json.dumps coerces them to strings."""
        return self.conductor.call("get_train_progress", timeout=10.0)

    def serve_status(self) -> Dict[str, Any]:
        """Serve apps/deployments/proxies, mirrored into the conductor
        KV by the Serve controller's reconcile loop."""
        status = self.conductor.call("kv_get", "serve:status", "serve",
                                     timeout=5.0)
        return status or {"applications": {}, "proxies": {}}

    def autoscaler_status(self) -> Dict[str, Any]:
        """Autoscaler reconcile state (KV mirror) + live pending demand
        from the conductor — the `ray status` analog."""
        import json as _json

        raw = self.conductor.call("kv_get", b"autoscaler:status",
                                  "autoscaler", timeout=5.0)
        status = _json.loads(raw.decode()) if raw else {}
        try:
            status["live_demand"] = self.conductor.call(
                "get_pending_demand", timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            status["live_demand"] = []
        return status

    def kvcache(self) -> Dict[str, Any]:
        """Paged-KV prefix cache: engine stats + the recent event tail
        (one payload so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_kvcache_stats", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_kvcache_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def speculation(self) -> Dict[str, Any]:
        """Speculative-decoding aggregate + the kvcache lane's
        spec_accept/spec_reject marker slice (one payload so the SPA's
        panel needs a single fetch)."""
        out = self.conductor.call("get_speculation_stats", timeout=10.0)
        try:
            events = self.conductor.call("get_kvcache_events", 10_000,
                                         timeout=5.0)
            out["events"] = [e for e in events if str(
                e.get("kind", "")).startswith("spec_")][-100:]
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def pipeline(self) -> Dict[str, Any]:
        """MPMD pipeline registry + the recent event tail (one payload
        so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_pipeline_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_pipeline_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def online(self) -> Dict[str, Any]:
        """Online-loop aggregate + the recent event tail (one payload
        so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_online_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_online_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def disagg(self) -> Dict[str, Any]:
        """Disaggregated-serving aggregate + the recent event tail (one
        payload so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_disagg_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_disagg_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def kvplane(self) -> Dict[str, Any]:
        """Global-KV-plane aggregate (arena tiers, prefix directory,
        routing outcomes) + the recent spill/tier2_hit/tier3_publish/
        tier3_adopt/directory_hit event tail (one payload so the SPA's
        panel needs a single fetch)."""
        out = self.conductor.call("get_kvplane_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_kvplane_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def autoscale(self) -> Dict[str, Any]:
        """Serving-autoscaler aggregate + the recent event tail (one
        payload so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_autoscale_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_autoscale_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def servefault(self) -> Dict[str, Any]:
        """Serving-fault-tolerance aggregate + the resilience lane's
        failover/replace/breaker_trip event slice (one payload so the
        SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_servefault_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call(
                "get_servefault_events", 100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def lora(self) -> Dict[str, Any]:
        """Multi-tenant LoRA aggregate + the recent page_in/evict/swap
        event tail (one payload so the SPA's panel needs a single
        fetch)."""
        out = self.conductor.call("get_lora_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_lora_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def gateway(self) -> Dict[str, Any]:
        """HTTP front-door aggregate + the recent accept/first_byte/
        preempt/rate_limit/disconnect event tail (one payload so the
        SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_gateway_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_gateway_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def oracle(self) -> Dict[str, Any]:
        """Step-time-oracle aggregate + the recent event tail (one
        payload so the SPA's panel needs a single fetch)."""
        out = self.conductor.call("get_oracle_status", timeout=10.0)
        try:
            out["events"] = self.conductor.call("get_oracle_events",
                                                100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def requesttrace(self) -> Dict[str, Any]:
        """Per-request flight-recorder aggregate (totals, p99
        attribution, slowest requests with phase breakdowns) + the
        recent kept-trace event tail (one payload so the SPA's panel
        needs a single fetch)."""
        out = self.conductor.call("get_requesttrace_status",
                                  timeout=10.0)
        try:
            out["events"] = self.conductor.call(
                "get_requesttrace_events", 100, timeout=5.0)
        except Exception:  # noqa: BLE001 — older conductor
            out["events"] = []
        return out

    def actor_detail(self, actor_id: str) -> Dict[str, Any]:
        """One actor's record + its worker + its recent task events —
        the actors-table drill-down."""
        actors = self.conductor.call("list_actors", timeout=5.0)
        rec = next((a for a in actors if a.get("actor_id") == actor_id),
                   None)
        if rec is None:
            return {"error": f"no actor {actor_id!r}"}
        addr = tuple(rec["address"]) if rec.get("address") else None
        if addr is None:  # PENDING/DEAD actor: nothing to join against
            return {"actor": rec, "worker": None, "recent_tasks": [],
                    "store": None}
        workers = self.conductor.call("list_workers", timeout=5.0)
        worker = next((w for w in workers if addr and w.get("address")
                       and tuple(w["address"]) == addr), None)
        events = self.conductor.call("get_task_events", 10_000,
                                     timeout=10.0)
        mine = [ev for ev in events
                if addr and ev.get("worker")
                and tuple(ev["worker"]) == addr][-100:]
        store = None
        if addr and worker is not None and worker.get("state") != "DEAD":
            try:
                store = self.pool.get(addr).call("store_stats",
                                                 timeout=3.0)
            except Exception:  # noqa: BLE001 — worker mid-restart
                pass
        return {"actor": rec, "worker": worker, "recent_tasks": mine,
                "store": store}


class DashboardServer:
    """aiohttp app on its own thread+loop — works beside a blocking
    conductor (in-process head) or standalone via `ray_tpu dashboard`."""

    def __init__(self, conductor_address: Tuple[str, int],
                 host: str = "127.0.0.1",
                 port: int = DEFAULT_DASHBOARD_PORT):
        self.data = _ClusterData(tuple(conductor_address))
        self.host, self.port = host, port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._started = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="dashboard", daemon=True)

    # ------------------------------------------------------------ handlers

    async def _call(self, fn, *args):
        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    async def _index(self, request):
        from aiohttp import web

        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "index.html")
        return web.FileResponse(path)

    def _json_route(self, fn):
        from aiohttp import web

        async def handler(request):
            try:
                return web.json_response(await self._call(fn))
            except Exception as e:  # noqa: BLE001 — surface, don't 500-html
                return web.json_response({"error": str(e)}, status=503)
        return handler

    async def _metrics(self, request):
        from aiohttp import web

        text = await self._call(self.data.metrics_text)
        return web.Response(text=text,
                            content_type="text/plain", charset="utf-8")

    # ------------------------------------------------------------ lifecycle

    def _make_app(self):
        from aiohttp import web

        d = self.data
        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/summary", self._json_route(d.summary))
        for name, method in [("nodes", "nodes"),
                             ("workers", "list_workers"),
                             ("actors", "list_actors"),
                             ("placement_groups", "list_placement_groups"),
                             ("jobs", "list_jobs")]:
            app.router.add_get(
                f"/api/{name}",
                self._json_route(lambda m=method: d.simple(m)))
        app.router.add_get("/api/objects", self._json_route(d.objects))
        app.router.add_get("/api/tasks", self._json_route(d.tasks_summary))
        app.router.add_get("/api/timeline", self._json_route(d.timeline))
        app.router.add_get("/api/logs",
                           self._json_route(
                               lambda: d.simple_args("get_recent_logs", 500)))
        app.router.add_get("/api/metrics", self._metrics)
        app.router.add_get("/api/serve", self._json_route(d.serve_status))
        app.router.add_get("/api/train", self._json_route(d.train_progress))
        app.router.add_get("/api/autoscaler",
                           self._json_route(d.autoscaler_status))
        app.router.add_get(
            "/api/resilience",
            self._json_route(lambda: d.simple("get_resilience_status")))
        app.router.add_get(
            "/api/weights",
            self._json_route(lambda: d.simple("get_weight_versions")))
        app.router.add_get("/api/kvcache", self._json_route(d.kvcache))
        app.router.add_get("/api/speculation",
                           self._json_route(d.speculation))
        app.router.add_get("/api/pipeline", self._json_route(d.pipeline))
        app.router.add_get("/api/online", self._json_route(d.online))
        app.router.add_get("/api/disagg", self._json_route(d.disagg))
        app.router.add_get("/api/kvplane", self._json_route(d.kvplane))
        app.router.add_get("/api/autoscale",
                           self._json_route(d.autoscale))
        app.router.add_get("/api/servefault",
                           self._json_route(d.servefault))
        app.router.add_get("/api/lora", self._json_route(d.lora))
        app.router.add_get("/api/gateway", self._json_route(d.gateway))
        app.router.add_get("/api/oracle", self._json_route(d.oracle))
        app.router.add_get("/api/requesttrace",
                           self._json_route(d.requesttrace))
        app.router.add_get(
            "/api/rpc",
            self._json_route(lambda: d.simple("get_rpc_stats")))

        async def actor_detail(request):
            from aiohttp import web

            try:
                return web.json_response(await self._call(
                    d.actor_detail, request.match_info["actor_id"]))
            except Exception as e:  # noqa: BLE001
                return web.json_response({"error": str(e)}, status=503)

        app.router.add_get("/api/actors/{actor_id}", actor_detail)
        return app

    def _run(self) -> None:
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop
        runner = web.AppRunner(self._make_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, self.host, self.port)
        loop.run_until_complete(site.start())
        # port 0 -> discover the bound port
        for s in site._server.sockets:  # noqa: SLF001 — aiohttp API gap
            self.port = s.getsockname()[1]
            break
        self._started.set()
        try:
            loop.run_forever()
        finally:
            loop.run_until_complete(runner.cleanup())
            loop.close()

    def start(self, timeout: float = 10.0) -> "DashboardServer":
        self._thread.start()
        if not self._started.wait(timeout):
            raise RuntimeError("dashboard failed to start")
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        if self._loop is not None:
            self._loop.call_soon_threadsafe(self._loop.stop)
        self._thread.join(timeout=5.0)


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="ray_tpu web dashboard")
    ap.add_argument("--address", required=True, help="conductor host:port")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=DEFAULT_DASHBOARD_PORT)
    args = ap.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    srv = DashboardServer((host, int(port)), host=args.host,
                          port=args.port).start()
    print(f"dashboard at {srv.url}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.stop()
