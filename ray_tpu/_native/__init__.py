"""Native (C++) components of ray_tpu — built with g++ at first import and
cached next to the sources (no pybind11 in this image; plain C ABI via
ctypes). See shm_store.cc for the object-store arena."""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "shm_store.cc")
_LIB = os.path.join(_DIR, "libshm_store.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _build() -> bool:
    # Per-process tmp name: N workers may race to build on a fresh checkout,
    # and two compilers writing one inode would publish a corrupt .so.
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17", "-o", tmp, _SRC,
           "-lpthread"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _LIB)
        return True
    except Exception:  # noqa: BLE001 — fall back to the pure-python store
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def load_shm_store() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the arena library; None if unavailable."""
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB) or \
                os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
            if not _build():
                _build_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError:
            _build_failed = True
            return None
        lib.rtpu_arena_create.restype = ctypes.c_void_p
        lib.rtpu_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rtpu_arena_attach.restype = ctypes.c_void_p
        lib.rtpu_arena_attach.argtypes = [ctypes.c_char_p]
        lib.rtpu_arena_alloc.restype = ctypes.c_uint64
        lib.rtpu_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_arena_free.restype = None
        lib.rtpu_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rtpu_arena_base.restype = ctypes.c_void_p
        lib.rtpu_arena_base.argtypes = [ctypes.c_void_p]
        lib.rtpu_arena_size.restype = ctypes.c_uint64
        lib.rtpu_arena_size.argtypes = [ctypes.c_void_p]
        lib.rtpu_arena_used.restype = ctypes.c_uint64
        lib.rtpu_arena_used.argtypes = [ctypes.c_void_p]
        lib.rtpu_arena_num_allocs.restype = ctypes.c_uint64
        lib.rtpu_arena_num_allocs.argtypes = [ctypes.c_void_p]
        lib.rtpu_arena_close.restype = None
        lib.rtpu_arena_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _lib = lib
        return lib


class Arena:
    """Thin OO wrapper over the C ABI. Owners allocate/free; attachers only
    read. ``view(offset, size)`` is a zero-copy memoryview into the shm."""

    def __init__(self, handle: int, lib: ctypes.CDLL, name: str, owner: bool):
        self._h = handle
        self._lib = lib
        self.name = name
        self.owner = owner
        base = lib.rtpu_arena_base(ctypes.c_void_p(handle))
        size = lib.rtpu_arena_size(ctypes.c_void_p(handle))
        self._mem = memoryview(
            (ctypes.c_ubyte * size).from_address(base)).cast("B")
        self._closed = False

    # -- construction -------------------------------------------------------
    @classmethod
    def create(cls, name: str, size: int) -> Optional["Arena"]:
        lib = load_shm_store()
        if lib is None:
            return None
        h = lib.rtpu_arena_create(name.encode(), size)
        if not h:
            return None
        return cls(h, lib, name, owner=True)

    @classmethod
    def attach(cls, name: str) -> Optional["Arena"]:
        lib = load_shm_store()
        if lib is None:
            return None
        h = lib.rtpu_arena_attach(name.encode())
        if not h:
            return None
        return cls(h, lib, name, owner=False)

    # -- allocator ----------------------------------------------------------
    def alloc(self, size: int) -> int:
        """Returns payload offset, or 0 if the arena is full."""
        return self._lib.rtpu_arena_alloc(ctypes.c_void_p(self._h), size)

    def free(self, offset: int) -> None:
        self._lib.rtpu_arena_free(ctypes.c_void_p(self._h), offset)

    def view(self, offset: int, size: int) -> memoryview:
        return self._mem[offset:offset + size]

    @property
    def buf(self) -> memoryview:
        """Whole-arena view (SharedMemory.buf-compatible)."""
        return self._mem

    @property
    def used_bytes(self) -> int:
        return self._lib.rtpu_arena_used(ctypes.c_void_p(self._h))

    @property
    def num_allocs(self) -> int:
        return self._lib.rtpu_arena_num_allocs(ctypes.c_void_p(self._h))

    # -- lifecycle ----------------------------------------------------------
    def unlink_only(self) -> None:
        """Remove the shm name WITHOUT unmapping — the safe shutdown path
        when zero-copy arrays may still be alive in this process (munmap
        under a live view is a SIGSEGV; the mapping dies with the process
        and the kernel reclaims memory once all mappings drop)."""
        self._closed = True
        if self.owner:
            try:
                os.unlink(f"/dev/shm/{self.name.lstrip('/')}")
            except OSError:
                pass

    def close(self, unlink: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._mem.release()
        except BufferError:
            # Zero-copy views are still exported somewhere; leave the
            # mapping in place (process is usually exiting) but still remove
            # the shm name so the memory is reclaimed once mappings drop.
            if unlink and self.owner:
                try:
                    os.unlink(f"/dev/shm/{self.name.lstrip('/')}")
                except OSError:
                    pass
            return
        self._lib.rtpu_arena_close(ctypes.c_void_p(self._h),
                                   1 if unlink else 0)

    def __del__(self):
        try:
            self.close(unlink=False)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
