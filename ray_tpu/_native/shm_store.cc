// Shared-memory slab arena — the C++ core of the ray_tpu object store.
//
// Native equivalent of the reference's plasma store allocator
// (/root/reference/src/ray/object_manager/plasma/: store_runner.h,
// dlmalloc.cc over mmap'd shm, object_lifecycle_manager) re-shaped for the
// ownership design of ray_tpu/_private/object_store.py: every worker process
// owns ONE posix-shm arena sized to its store cap; objects are carved out of
// it by a boundary-tag allocator with segregated free-list bins (a compact
// dlmalloc analog), and peers map the whole arena once, then read any object
// at (offset, size) zero-copy — instead of one shm_open+mmap per object.
//
// Concurrency contract: only the OWNING process allocates/frees (single-
// writer ownership, reference reference_count.h:61); a process-local pthread
// mutex serializes its threads. Readers never touch allocator metadata.
//
// Exposed as a C ABI for ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <pthread.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x7261795f747075ULL;  // "ray_tpu"
constexpr uint64_t kAlign = 64;  // payload sizes rounded to 64B to bound
                                 // fragmentation; payloads themselves are
                                 // 16-byte aligned (block + 16B header)
constexpr uint64_t kHeaderBytes = 4096;           // arena header page
constexpr uint64_t kBlockHdr = 16;                // size_flags + prev_size
constexpr uint64_t kMinPayload = 64;              // min split remainder
constexpr int kBins = 48;
constexpr uint64_t kUsedBit = 1ULL;

// Block layout (offsets relative to arena base):
//   [size_flags u64][prev_size u64][payload ...]
// size = total block bytes incl. header; LSB of size_flags = in-use.
// Free blocks keep {next_free u64, prev_free u64} at payload start.

struct ArenaHeader {
  uint64_t magic;
  uint64_t arena_size;   // total mapping size
  uint64_t heap_start;   // first block offset
  uint64_t heap_end;
  uint64_t used_bytes;   // payload bytes currently allocated
  uint64_t num_allocs;
  uint64_t bins[kBins];  // free-list heads (0 = empty)
};

struct Handle {
  uint8_t* base;
  uint64_t size;
  std::string name;
  bool owner;
  pthread_mutex_t lock;
};

inline ArenaHeader* hdr(Handle* h) {
  return reinterpret_cast<ArenaHeader*>(h->base);
}
inline uint64_t& size_flags(Handle* h, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(h->base + off);
}
inline uint64_t& prev_size(Handle* h, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(h->base + off + 8);
}
inline uint64_t block_size(Handle* h, uint64_t off) {
  return size_flags(h, off) & ~kUsedBit;
}
inline bool block_used(Handle* h, uint64_t off) {
  return size_flags(h, off) & kUsedBit;
}
inline uint64_t& next_free(Handle* h, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(h->base + off + kBlockHdr);
}
inline uint64_t& prev_free(Handle* h, uint64_t off) {
  return *reinterpret_cast<uint64_t*>(h->base + off + kBlockHdr + 8);
}

int bin_index(uint64_t size) {
  // log2 size classes starting at 128B blocks.
  int b = 0;
  uint64_t s = size >> 7;
  while (s > 1 && b < kBins - 1) {
    s >>= 1;
    ++b;
  }
  return b;
}

void freelist_insert(Handle* h, uint64_t off) {
  ArenaHeader* a = hdr(h);
  int b = bin_index(block_size(h, off));
  next_free(h, off) = a->bins[b];
  prev_free(h, off) = 0;
  if (a->bins[b]) prev_free(h, a->bins[b]) = off;
  a->bins[b] = off;
}

void freelist_remove(Handle* h, uint64_t off) {
  ArenaHeader* a = hdr(h);
  int b = bin_index(block_size(h, off));
  uint64_t nxt = next_free(h, off), prv = prev_free(h, off);
  if (prv) {
    next_free(h, prv) = nxt;
  } else {
    a->bins[b] = nxt;
  }
  if (nxt) prev_free(h, nxt) = prv;
}

uint64_t next_block(Handle* h, uint64_t off) {
  return off + block_size(h, off);
}

}  // namespace

extern "C" {

// Create a new arena of `size` bytes backed by /dev/shm/<name>.
// Returns an opaque handle or nullptr.
void* rtpu_arena_create(const char* name, uint64_t size) {
  if (size < kHeaderBytes + 4 * kMinPayload) return nullptr;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* base =
      mmap(nullptr, size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) {
    shm_unlink(name);
    return nullptr;
  }
  Handle* h = new Handle{static_cast<uint8_t*>(base), size, name, true,
                         PTHREAD_MUTEX_INITIALIZER};
  pthread_mutex_init(&h->lock, nullptr);
  ArenaHeader* a = hdr(h);
  std::memset(a, 0, sizeof(ArenaHeader));
  a->magic = kMagic;
  a->arena_size = size;
  a->heap_start = kHeaderBytes;
  // Keep every block size 8-aligned: an odd heap_end would leave a tail gap
  // whose 'next block' header read lands on unaligned (or out-of-range)
  // bytes during coalescing.
  a->heap_end = size & ~7ULL;
  // one giant free block spans the heap
  uint64_t off = a->heap_start;
  size_flags(h, off) = (a->heap_end - a->heap_start) & ~kUsedBit;
  prev_size(h, off) = 0;
  freelist_insert(h, off);
  return h;
}

// Map an existing arena read-write (readers only read payload bytes).
void* rtpu_arena_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, static_cast<uint64_t>(st.st_size),
                    PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (base == MAP_FAILED) return nullptr;
  Handle* h = new Handle{static_cast<uint8_t*>(base),
                         static_cast<uint64_t>(st.st_size), name, false,
                         PTHREAD_MUTEX_INITIALIZER};
  pthread_mutex_init(&h->lock, nullptr);
  if (hdr(h)->magic != kMagic) {
    munmap(base, h->size);
    delete h;
    return nullptr;
  }
  return h;
}

// Allocate `size` payload bytes; returns payload offset or 0 on failure
// (0 is inside the header page, never a valid payload offset).
uint64_t rtpu_arena_alloc(void* handle, uint64_t size) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h->owner) return 0;
  uint64_t need = kBlockHdr + ((size + kAlign - 1) & ~(kAlign - 1));
  if (need < kBlockHdr + kMinPayload) need = kBlockHdr + kMinPayload;
  pthread_mutex_lock(&h->lock);
  ArenaHeader* a = hdr(h);
  uint64_t off = 0;
  for (int b = bin_index(need); b < kBins && !off; ++b) {
    // first fit within the bin (bounded scan keeps alloc O(1)-ish)
    uint64_t cur = a->bins[b];
    int scanned = 0;
    while (cur && scanned < 32) {
      if (block_size(h, cur) >= need) {
        off = cur;
        break;
      }
      cur = next_free(h, cur);
      ++scanned;
    }
  }
  if (!off) {
    pthread_mutex_unlock(&h->lock);
    return 0;
  }
  freelist_remove(h, off);
  uint64_t bsize = block_size(h, off);
  if (bsize - need >= kBlockHdr + kMinPayload) {
    // split: tail becomes a new free block
    uint64_t tail = off + need;
    size_flags(h, tail) = (bsize - need) & ~kUsedBit;
    prev_size(h, tail) = need;
    freelist_insert(h, tail);
    uint64_t after = next_block(h, tail);
    if (after < a->heap_end) prev_size(h, after) = bsize - need;
    bsize = need;
  }
  size_flags(h, off) = bsize | kUsedBit;
  a->used_bytes += bsize;
  a->num_allocs += 1;
  pthread_mutex_unlock(&h->lock);
  return off + kBlockHdr;
}

// Free a payload offset returned by rtpu_arena_alloc.
void rtpu_arena_free(void* handle, uint64_t payload_off) {
  Handle* h = static_cast<Handle*>(handle);
  if (!h->owner || payload_off < kHeaderBytes + kBlockHdr) return;
  uint64_t off = payload_off - kBlockHdr;
  pthread_mutex_lock(&h->lock);
  ArenaHeader* a = hdr(h);
  if (!block_used(h, off)) {  // double free — ignore
    pthread_mutex_unlock(&h->lock);
    return;
  }
  uint64_t bsize = block_size(h, off);
  a->used_bytes -= bsize;
  a->num_allocs -= 1;
  size_flags(h, off) = bsize & ~kUsedBit;
  // coalesce forward
  uint64_t nxt = off + bsize;
  if (nxt < a->heap_end && !block_used(h, nxt)) {
    freelist_remove(h, nxt);
    bsize += block_size(h, nxt);
    size_flags(h, off) = bsize & ~kUsedBit;
  }
  // coalesce backward
  if (off > a->heap_start) {
    uint64_t prv = off - prev_size(h, off);
    if (!block_used(h, prv)) {
      freelist_remove(h, prv);
      bsize += block_size(h, prv);
      off = prv;
      size_flags(h, off) = bsize & ~kUsedBit;
    }
  }
  uint64_t after = off + bsize;
  if (after < a->heap_end) prev_size(h, after) = bsize;
  freelist_insert(h, off);
  pthread_mutex_unlock(&h->lock);
}

uint8_t* rtpu_arena_base(void* handle) {
  return static_cast<Handle*>(handle)->base;
}

uint64_t rtpu_arena_size(void* handle) {
  return static_cast<Handle*>(handle)->size;
}

uint64_t rtpu_arena_used(void* handle) {
  return hdr(static_cast<Handle*>(handle))->used_bytes;
}

uint64_t rtpu_arena_num_allocs(void* handle) {
  return hdr(static_cast<Handle*>(handle))->num_allocs;
}

// Detach the mapping (readers and owners); owner additionally unlinks the
// shm name if `unlink` is nonzero.
void rtpu_arena_close(void* handle, int unlink_name) {
  Handle* h = static_cast<Handle*>(handle);
  munmap(h->base, h->size);
  if (h->owner && unlink_name) shm_unlink(h->name.c_str());
  pthread_mutex_destroy(&h->lock);
  delete h;
}

}  // extern "C"
