"""Shared-memory mutable-object channels — analog of the reference's
python/ray/experimental/channel.py (:16-56 _create_channel_ref — mutable
plasma objects) + src/ray/core_worker/experimental_mutable_object_manager.h.

A Channel is a single-slot SPSC mailbox in POSIX shared memory: the writer
blocks until the reader has acked the previous value (the reference's
"mutable object" write-acquire/read-release protocol), so repeated compiled
DAG invocations reuse one buffer with zero allocation and zero RPC.

Wakeup design: payload + seq/ack live in shm (peeks are ~350ns); each
direction additionally has a named-FIFO *doorbell*. A waiter spins a short
window (microsecond latency when cores are free) and then parks in
select() on the doorbell — a kernel wakeup, which is the only thing that
works on an oversubscribed host (pure spinning burns whole scheduler quanta
on a 1-core box, and sched_yield is a near no-op under EEVDF).

Header layout (24 bytes): seq u64 | ack u64 | payload_len u64. A seq of
2**64-1 marks the channel closed."""
from __future__ import annotations

import os
import select
import struct
import tempfile
import time
from multiprocessing import shared_memory
from typing import Optional, Tuple

_HDR = struct.Struct("<QQQ")
_CLOSED = (1 << 64) - 1
DEFAULT_CAPACITY = 16 * 1024 * 1024
# ~70us busy window before parking — but only when a spare core can be
# burning it; on a 1-core host spinning just delays the peer's schedule.
_SPIN_LIMIT = 200 if (os.cpu_count() or 1) > 1 else 0
_PARK_SLICE_S = 0.05       # select timeout; doorbell normally wakes us first


class ChannelClosedError(Exception):
    pass


class Channel:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 _attach_name: Optional[str] = None):
        self.capacity = capacity
        if _attach_name is None:
            self._shm = shared_memory.SharedMemory(
                create=True, size=_HDR.size + capacity)
            self._shm.buf[:_HDR.size] = _HDR.pack(0, 0, 0)
            self._owner = True
            for path in (self._fifo_path("d"), self._fifo_path("a")):
                os.mkfifo(path)
        else:
            self._shm = shared_memory.SharedMemory(name=_attach_name)
            self._owner = False
        self._fd_data: Optional[int] = None
        self._fd_ack: Optional[int] = None

    @property
    def name(self) -> str:
        return self._shm.name

    def _fifo_path(self, tag: str) -> str:
        return os.path.join(tempfile.gettempdir(),
                            f"rtpu_{self._shm.name.lstrip('/')}_{tag}.fifo")

    def _fd(self, tag: str) -> int:
        # O_RDWR so open never blocks/ENXIOs regardless of peer state (Linux
        # allows it on FIFOs) and a doorbell is never lost for lack of reader.
        attr = "_fd_data" if tag == "d" else "_fd_ack"
        fd = getattr(self, attr)
        if fd is None:
            fd = os.open(self._fifo_path(tag), os.O_RDWR | os.O_NONBLOCK)
            setattr(self, attr, fd)
        return fd

    def _ring(self, tag: str) -> None:
        try:
            os.write(self._fd(tag), b"\x01")
        except (BlockingIOError, OSError):  # full pipe still wakes the peer
            pass

    def _park(self, tag: str, deadline: Optional[float]) -> None:
        slice_s = _PARK_SLICE_S
        if deadline is not None:
            slice_s = min(slice_s, max(0.0, deadline - time.monotonic()))
        fd = self._fd(tag)
        r, _, _ = select.select([fd], [], [], slice_s)
        if r:
            try:
                os.read(fd, 4096)  # drain doorbell bytes
            except (BlockingIOError, OSError):
                pass

    def __reduce__(self):
        return (Channel, (self.capacity, self._shm.name))

    # -- writer side --------------------------------------------------------
    def write(self, payload: bytes, timeout: Optional[float] = None) -> None:
        if len(payload) > self.capacity:
            raise ValueError(
                f"value of {len(payload)} bytes exceeds channel capacity "
                f"{self.capacity}; recompile with a larger "
                f"buffer_size_bytes")
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, ack, _ = _HDR.unpack_from(self._shm.buf, 0)
            if seq == _CLOSED:
                raise ChannelClosedError
            if ack == seq:  # previous value consumed — slot free
                break
            spins += 1
            if spins > _SPIN_LIMIT:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(
                        "channel writer timed out waiting for ack")
                self._park("a", deadline)
        self._shm.buf[_HDR.size:_HDR.size + len(payload)] = payload
        _HDR.pack_into(self._shm.buf, 0, seq + 1, ack, len(payload))
        self._ring("d")

    # -- reader side --------------------------------------------------------
    def read(self, last_seq: int, timeout: Optional[float] = None
             ) -> Tuple[int, bytes]:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        while True:
            seq, ack, length = _HDR.unpack_from(self._shm.buf, 0)
            if seq == _CLOSED:
                raise ChannelClosedError
            if seq != last_seq:
                data = bytes(self._shm.buf[_HDR.size:_HDR.size + length])
                _HDR.pack_into(self._shm.buf, 0, seq, seq, length)  # ack
                self._ring("a")
                return seq, data
            spins += 1
            if spins > _SPIN_LIMIT:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("channel reader timed out")
                self._park("d", deadline)

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        try:
            _HDR.pack_into(self._shm.buf, 0, _CLOSED, 0, 0)
            self._ring("d")
            self._ring("a")
        except Exception:  # noqa: BLE001 — already unlinked
            pass

    def release(self) -> None:
        for attr in ("_fd_data", "_fd_ack"):
            fd = getattr(self, attr)
            if fd is not None:
                try:
                    os.close(fd)
                except OSError:
                    pass
                setattr(self, attr, None)
        try:
            self._shm.close()
        except Exception:  # noqa: BLE001
            pass

    def destroy(self) -> None:
        self.close()
        self.release()
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:  # noqa: BLE001
                pass
            for tag in ("d", "a"):
                try:
                    os.unlink(self._fifo_path(tag))
                except OSError:
                    pass
