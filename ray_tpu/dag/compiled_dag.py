"""Compiled DAG execution — analog of the reference's python/ray/dag/
compiled_dag_node.py (CompiledDAG :174, do_exec_compiled_task :43): at
compile time every cross-process edge gets a pre-allocated shared-memory
Channel and every participating actor pins a loop that reads its input
channels, runs its methods, and writes downstream — so repeated invocations
bypass task submission entirely.

TPU relevance: this is the microsecond-scale host-side orchestration path
for pipelines of jitted steps (e.g. multi-stage inference) where per-call
RPC overhead would dominate device compute."""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from .channel import Channel, ChannelClosedError
from .dag_node import (ClassMethodNode, DAGNode, FunctionNode,
                       InputAttributeNode, InputNode, MultiOutputNode)


class _ExecError:
    """Error sentinel forwarded through channels so a failure in one stage
    propagates to the driver instead of deadlocking downstream readers."""

    def __init__(self, error: BaseException, tb: str):
        self.error = error
        self.tb = tb


# -- arg templates ----------------------------------------------------------
# ("const", v) | ("local", node_id) | ("chan", key, extract_key|None)
# | ("list", [t...], type) | ("dict", {k: t})

def _template(obj, node_actor: Dict[int, str], my_actor: str,
              edge_key) -> tuple:
    if isinstance(obj, InputAttributeNode):
        return ("chan", ("input", my_actor), obj._key)
    if isinstance(obj, InputNode):
        return ("chan", ("input", my_actor), None)
    if isinstance(obj, ClassMethodNode):
        if node_actor[obj._id] == my_actor:
            return ("local", obj._id)
        return ("chan", edge_key(obj._id), None)
    if isinstance(obj, DAGNode):
        raise TypeError(
            f"{type(obj).__name__} cannot appear inside a compiled DAG")
    if isinstance(obj, (list, tuple)):
        return ("list", [_template(x, node_actor, my_actor, edge_key)
                         for x in obj], type(obj))
    if isinstance(obj, dict):
        return ("dict", {k: _template(v, node_actor, my_actor, edge_key)
                         for k, v in obj.items()})
    return ("const", obj)


def _resolve_template(t, local: Dict[int, Any], vals: Dict[Any, Any]):
    kind = t[0]
    if kind == "const":
        return t[1]
    if kind == "local":
        return local[t[1]]
    if kind == "chan":
        v = vals[t[1]]
        if isinstance(v, _ExecError):
            return v
        if t[2] is not None:
            return InputAttributeNode.extract(v, t[2])
        return v
    if kind == "list":
        return t[2](_resolve_template(x, local, vals) for x in t[1])
    if kind == "dict":
        return {k: _resolve_template(v, local, vals)
                for k, v in t[1].items()}
    raise ValueError(f"bad template {t!r}")


def _contains_error(obj) -> Optional[_ExecError]:
    if isinstance(obj, _ExecError):
        return obj
    if isinstance(obj, (list, tuple)):
        for x in obj:
            e = _contains_error(x)
            if e is not None:
                return e
    if isinstance(obj, dict):
        for x in obj.values():
            e = _contains_error(x)
            if e is not None:
                return e
    return None


def run_actor_loop(instance, spec_bytes: bytes) -> str:
    """Pinned per-actor loop — reference compiled_dag_node.py
    do_exec_compiled_task. Runs inside the actor's execution slot until the
    driver tears the DAG down (channels closed)."""
    import traceback

    spec = cloudpickle.loads(spec_bytes)
    in_chans: Dict[Any, Channel] = spec["in_channels"]
    ops: List[dict] = spec["ops"]
    last_seq = {k: 0 for k in in_chans}

    def chan_keys(t, acc):
        if t[0] == "chan":
            acc.append(t[1])
        elif t[0] == "list":
            for x in t[1]:
                chan_keys(x, acc)
        elif t[0] == "dict":
            for x in t[1].values():
                chan_keys(x, acc)

    for op in ops:
        need: List[Any] = []
        for t in op["args"]:
            chan_keys(t, need)
        for t in op["kwargs"].values():
            chan_keys(t, need)
        op["_need"] = list(dict.fromkeys(need))  # dedup, keep order

    try:
        while True:
            vals: Dict[Any, Any] = {}
            local: Dict[int, Any] = {}
            for op in ops:
                # Read each upstream channel at FIRST USE, not all upfront:
                # a DAG that revisits this actor (A->B->A, the pipeline
                # fwd/bwd shape) would otherwise block on the B->A edge
                # before ever producing the value B is waiting for.
                for key in op["_need"]:
                    if key not in vals:
                        seq, data = in_chans[key].read(last_seq[key])
                        last_seq[key] = seq
                        vals[key] = cloudpickle.loads(data)
                args = [_resolve_template(t, local, vals)
                        for t in op["args"]]
                kwargs = {k: _resolve_template(t, local, vals)
                          for k, t in op["kwargs"].items()}
                err = _contains_error(args) or _contains_error(
                    list(kwargs.values()))
                if err is not None:
                    result: Any = err  # forward upstream failure
                else:
                    try:
                        result = getattr(instance, op["method"])(
                            *args, **kwargs)
                    except BaseException as e:  # noqa: BLE001 — forward
                        result = _ExecError(e, traceback.format_exc())
                local[op["node_id"]] = result
                if op["out_channels"]:
                    payload = cloudpickle.dumps(result)
                    for ch in op["out_channels"]:
                        ch.write(payload)
    except ChannelClosedError:
        return "torn_down"
    finally:
        for ch in in_chans.values():
            ch.release()
        for op in ops:
            for ch in op["out_channels"]:
                ch.release()


class CompiledDAGFuture:
    """Handle for one compiled invocation — reference CompiledDAGRef.
    Results MUST be consumed in submission order (single-slot channels)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value: Any = None
        self._have = False

    def get(self, timeout: Optional[float] = 30.0):
        if not self._have:
            self._value = self._dag._read_output(self._seq, timeout)
            self._have = True
        if isinstance(self._value, _ExecError):
            raise RuntimeError(
                f"compiled DAG stage failed: {self._value.error!r}\n"
                f"--- remote traceback ---\n{self._value.tb}"
            ) from self._value.error
        return self._value


class CompiledDAG:
    """Reference compiled_dag_node.py:174."""

    def __init__(self, output_node: DAGNode,
                 buffer_size_bytes: int = 16 * 1024 * 1024):
        self._buffer = buffer_size_bytes
        self._lock = threading.Lock()
        self._exec_seq = 0
        self._read_seq = 0
        self._torn_down = False
        self._build(output_node)

    # -- compilation --------------------------------------------------------
    def _build(self, output_node: DAGNode):
        topo = output_node._topo_order()
        if isinstance(output_node, (InputNode, InputAttributeNode)):
            raise ValueError("a compiled DAG must end in an actor method")
        for n in topo:
            if isinstance(n, FunctionNode):
                raise NotImplementedError(
                    "compiled DAGs support actor methods only (reference "
                    "restriction); use .execute() for task nodes")

        out_nodes: List[ClassMethodNode]
        if isinstance(output_node, MultiOutputNode):
            out_nodes = []
            for n in output_node._outputs:
                if not isinstance(n, ClassMethodNode):
                    raise ValueError("MultiOutputNode members must be actor "
                                     "method nodes")
                out_nodes.append(n)
        else:
            assert isinstance(output_node, ClassMethodNode)
            out_nodes = [output_node]
        self._multi_output = isinstance(output_node, MultiOutputNode)

        method_nodes = [n for n in topo if isinstance(n, ClassMethodNode)]
        node_actor = {n._id: n._actor.actor_id for n in method_nodes}
        actors: Dict[str, Any] = {n._actor.actor_id: n._actor
                                  for n in method_nodes}

        # channels
        self._channels: List[Channel] = []

        def new_chan() -> Channel:
            ch = Channel(self._buffer)
            self._channels.append(ch)
            return ch

        edge_chans: Dict[Tuple[int, int], Channel] = {}
        input_chans: Dict[str, Channel] = {}
        self._out_chans: List[Channel] = []

        # per-actor spec under construction
        spec: Dict[str, dict] = {aid: {"in_channels": {}, "ops": []}
                                 for aid in actors}

        for n in method_nodes:
            aid = node_actor[n._id]

            def edge_key(src_id, _n=n):
                return (src_id, _n._id)

            args_t = [_template(a, node_actor, aid, edge_key)
                      for a in n._bound_args]
            kwargs_t = {k: _template(v, node_actor, aid, edge_key)
                        for k, v in n._bound_kwargs.items()}
            # wire input channels for any chan template this op references
            def wire(t):
                if t[0] == "chan":
                    key = t[1]
                    if key[0] == "input":
                        if aid not in input_chans:
                            input_chans[aid] = new_chan()
                        spec[aid]["in_channels"][key] = input_chans[aid]
                    else:
                        src_id = key[0]
                        if key not in edge_chans:
                            edge_chans[key] = new_chan()
                        spec[aid]["in_channels"][key] = edge_chans[key]
                        # register as an output of the source op (once —
                        # a node consumed twice by the same downstream op
                        # must not be double-written per iteration)
                        src_aid = node_actor[src_id]
                        for op in spec[src_aid]["ops"]:
                            if op["node_id"] == src_id and \
                                    edge_chans[key] not in op["out_channels"]:
                                op["out_channels"].append(edge_chans[key])
                elif t[0] == "list":
                    for x in t[1]:
                        wire(x)
                elif t[0] == "dict":
                    for x in t[1].values():
                        wire(x)

            for t in args_t:
                wire(t)
            for t in kwargs_t.values():
                wire(t)
            spec[aid]["ops"].append({
                "node_id": n._id, "method": n._method_name,
                "args": args_t, "kwargs": kwargs_t, "out_channels": []})

        # driver output channels
        for n in out_nodes:
            ch = new_chan()
            self._out_chans.append(ch)
            aid = node_actor[n._id]
            for op in spec[aid]["ops"]:
                if op["node_id"] == n._id:
                    op["out_channels"].append(ch)

        self._input_chans = input_chans
        # launch the pinned loops
        from ray_tpu.actor import ActorMethod
        self._loop_refs = []
        for aid, s in spec.items():
            m = ActorMethod(actors[aid], "__ray_tpu_compiled_loop__")
            self._loop_refs.append(m.remote(cloudpickle.dumps(s)))

    # -- execution ----------------------------------------------------------
    def execute(self, *input_args, **input_kwargs) -> CompiledDAGFuture:
        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        if input_args and input_kwargs:
            raise TypeError(
                "compiled DAG input must be all-positional or all-keyword")
        if input_kwargs:
            payload = cloudpickle.dumps(dict(input_kwargs))
        elif len(input_args) == 1:
            payload = cloudpickle.dumps(input_args[0])
        else:
            payload = cloudpickle.dumps(tuple(input_args))
        with self._lock:
            for ch in self._input_chans.values():
                ch.write(payload, timeout=30.0)
            self._exec_seq += 1
            return CompiledDAGFuture(self, self._exec_seq)

    def _read_output(self, seq: int, timeout: Optional[float]):
        with self._lock:
            if seq != self._read_seq + 1:
                raise RuntimeError(
                    "compiled DAG results must be consumed in submission "
                    f"order (asked for #{seq}, next is #{self._read_seq + 1})")
            outs = []
            for ch in self._out_chans:
                got_seq, data = ch.read(seq - 1, timeout=timeout)
                assert got_seq == seq, (got_seq, seq)
                outs.append(cloudpickle.loads(data))
            self._read_seq = seq
        err = _contains_error(outs)
        if err is not None:
            return err
        return outs if self._multi_output else outs[0]

    # -- lifecycle ----------------------------------------------------------
    def teardown(self, wait: bool = True):
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._channels:
            ch.close()
        if wait:
            import ray_tpu
            for ref in self._loop_refs:
                try:
                    ray_tpu.get(ref, timeout=10.0)
                except Exception:  # noqa: BLE001 — actor may be dead
                    pass
        for ch in self._channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown(wait=False)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
