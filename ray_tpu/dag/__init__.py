"""ray_tpu.dag — lazy DAG + compiled execution, analog of the reference's
python/ray/dag/ and python/ray/experimental/channel.py (see SURVEY.md §2.3).
"""
from .channel import Channel, ChannelClosedError  # noqa: F401
from .compiled_dag import CompiledDAG, CompiledDAGFuture  # noqa: F401
from .dag_node import (ClassMethodNode, DAGNode, FunctionNode,  # noqa: F401
                       InputAttributeNode, InputNode, MultiOutputNode)

__all__ = ["DAGNode", "InputNode", "InputAttributeNode", "FunctionNode",
           "ClassMethodNode", "MultiOutputNode", "CompiledDAG",
           "CompiledDAGFuture", "Channel", "ChannelClosedError"]
