"""Lazy DAG API — analog of the reference's python/ray/dag/
(dag_node.py DAGNode, input_node.py InputNode/InputAttributeNode,
output_node.py MultiOutputNode, function_node.py, class_node.py).

``fn.bind(...)`` / ``actor.method.bind(...)`` build the graph lazily;
``.execute(input)`` runs it through the normal task/actor path;
``.experimental_compile()`` (compiled_dag.py) pins actor loops over
shared-memory channels."""
from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional

_node_counter = itertools.count()


class DAGNode:
    def __init__(self, args: tuple = (), kwargs: Optional[dict] = None):
        self._bound_args = args
        self._bound_kwargs = dict(kwargs or {})
        self._id = next(_node_counter)

    # -- traversal ----------------------------------------------------------
    def _upstream(self) -> List["DAGNode"]:
        found: List[DAGNode] = []

        def scan(obj):
            if isinstance(obj, DAGNode):
                found.append(obj)
            elif isinstance(obj, (list, tuple)):
                for x in obj:
                    scan(x)
            elif isinstance(obj, dict):
                for x in obj.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for v in self._bound_kwargs.values():
            scan(v)
        return found

    def _resolve_args(self, resolved: Dict[int, Any]):
        def swap(obj):
            if isinstance(obj, DAGNode):
                return resolved[obj._id]
            if isinstance(obj, (list, tuple)):
                return type(obj)(swap(x) for x in obj)
            if isinstance(obj, dict):
                return {k: swap(v) for k, v in obj.items()}
            return obj

        return (tuple(swap(a) for a in self._bound_args),
                {k: swap(v) for k, v in self._bound_kwargs.items()})

    def _topo_order(self) -> List["DAGNode"]:
        order: List[DAGNode] = []
        seen: set = set()

        def visit(n: DAGNode):
            if n._id in seen:
                return
            seen.add(n._id)
            for up in n._upstream():
                visit(up)
            order.append(n)

        visit(self)
        return order

    # -- execution ----------------------------------------------------------
    def execute(self, *input_args, **input_kwargs):
        """Run the DAG once via normal task/actor submission — reference
        dag_node.py execute(). Returns ObjectRef(s) for the output node."""
        resolved: Dict[int, Any] = {}
        for node in self._topo_order():
            resolved[node._id] = node._execute_impl(resolved, input_args,
                                                    input_kwargs)
        return resolved[self._id]

    def _execute_impl(self, resolved, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self, buffer_size_bytes: int = 16 * 1024 * 1024):
        from .compiled_dag import CompiledDAG
        return CompiledDAG(self, buffer_size_bytes=buffer_size_bytes)


class InputNode(DAGNode):
    """The DAG's runtime input — reference input_node.py. Usable as a
    context manager: ``with InputNode() as inp: ...``."""

    def __init__(self):
        super().__init__()

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *a):
        return False

    def __getattr__(self, key: str) -> "InputAttributeNode":
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)

    def _execute_impl(self, resolved, input_args, input_kwargs):
        if input_args and input_kwargs:
            raise TypeError(
                "DAG input must be all-positional or all-keyword")
        if input_kwargs:
            return dict(input_kwargs)
        if len(input_args) == 1:
            return input_args[0]
        return tuple(input_args)


class InputAttributeNode(DAGNode):
    """inp.key / inp[i] — reference input_node.py InputAttributeNode."""

    def __init__(self, parent: InputNode, key: Any):
        super().__init__()
        self._parent = parent
        self._key = key

    def _upstream(self):
        return [self._parent]

    def _execute_impl(self, resolved, input_args, input_kwargs):
        if isinstance(self._key, str) and input_kwargs and \
                self._key in input_kwargs:
            return input_kwargs[self._key]
        base = resolved.get(self._parent._id)
        if base is None:
            base = input_args[0] if len(input_args) == 1 else tuple(input_args)
        if isinstance(self._key, str) and isinstance(base, dict):
            return base[self._key]
        if isinstance(self._key, str):
            return getattr(base, self._key)
        return base[self._key]

    @staticmethod
    def extract(value, key):
        if isinstance(key, str) and isinstance(value, dict):
            return value[key]
        if isinstance(key, str):
            return getattr(value, key)
        return value[key]


class FunctionNode(DAGNode):
    """fn.bind(...) on a @remote function — reference function_node.py."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, resolved, input_args, input_kwargs):
        args, kwargs = self._resolve_args(resolved)
        return self._remote_fn.remote(*args, **kwargs)


class ClassMethodNode(DAGNode):
    """actor.method.bind(...) — reference class_node.py ClassMethodNode
    (bound to a *live* actor handle, as in the compiled-DAG examples)."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name

    def _execute_impl(self, resolved, input_args, input_kwargs):
        args, kwargs = self._resolve_args(resolved)
        return getattr(self._actor, self._method_name).remote(*args, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves into one output — reference output_node.py."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=tuple(outputs))
        self._outputs = list(outputs)

    def _execute_impl(self, resolved, input_args, input_kwargs):
        return [resolved[n._id] for n in self._outputs]
