"""Multi-tenant LoRA serving: a paged adapter pool over one resident
base model (the ROADMAP's scenario-diversity item — millions of users
means per-tenant fine-tunes, not one monolith; S-LoRA's serving shape
rebuilt on machinery this repo already owns).

The pieces, and where each lives:

- **AdapterPool** (here): adapters page through a refcounted LRU pool
  exactly like KV blocks page through ``models/kvcache.py`` — device-
  resident stacks ``A [P, L, in, r_max]`` / ``B [P, L, r_max, out]``
  per LoRA-target leaf (``models.generate.lora_targets``), one pool
  row per adapter, row 0 reserved as the NULL adapter (zero A/B,
  scale 0 — the base model). ``acquire(tenant)`` pins a resident
  adapter (hit) or pages it in (miss: fetch → zero-pad to ``r_max`` →
  write its row), evicting the least-recently-used UNPINNED row under
  pressure; pinned rows are never evicted. Acquisition runs on the
  SUBMITTING thread (models/engine.py submit/adopt_prefill), so a cold
  tenant's page-in can never stall another tenant's decode tick. Pool
  row writes are DONATED jits — O(row) in place, never an O(pool)
  stack copy (the models/kvcache.py write discipline; at 64 slots x
  32 layers a copying write moves the whole pool per page-in). The
  donation is tick-safe the same way the kvcache's is: every read of
  the stacks (the decode tick via ``dispatch_tick``, the prefill
  merge's ``adapter_slice``) and every donated write dispatches under
  the pool lock, so same-device stream order makes dispatch the only
  critical section while the compute overlaps freely. shardlint's
  ``undonated-pool-write`` rule guards the discipline.
- **Cross-tenant batched decode** (models/engine.py ``_tick_lora`` +
  the model families' ``*_decode(lora=)``): one decode tick serves
  mixed tenants via per-slot adapter indices gathering each slot's
  A/B out of these stacks — ``base @ x + scatter-gathered (B·A) @ x``
  at the target leaves. Null-adapter slots are bit-identical to the
  base-only engine (the correctness oracle, asserted in
  tests/test_lora.py).
- **Paging source**: :class:`FabricAdapterSource` fetches adapters on
  demand through :class:`~ray_tpu.weights.WeightSubscriber` from the
  weight fabric's (delta) publications under ``lora/<tenant>`` — a
  tenant's publish marks it dirty (pubsub) and the next acquire
  hot-swaps the new version into its row BETWEEN ticks, without
  touching the base or any other tenant's in-flight requests.
  :class:`LocalAdapterSource` is the clusterless twin (tests, the
  in-process load harness).
- **Tenant routing** (serve/disagg.py): ``DisaggRouter.generate``
  carries a ``tenant`` tag (defaulting to serve/multiplex.py's
  multiplexed-model-id — the request-side plumbing reused as the
  tenant tag), adds tenant-affinity beside prefix-affinity, keeps
  per-tenant shed/SLO/latency counters, and the prefix cache keys
  entries by (tenant, prompt) (``models/kvcache.py`` namespaces).
- **Per-tenant online loop** (online/lora.py ``TenantLoraTrainer``):
  adapter-only gradients against the frozen base, published as deltas
  that hot-swap through the dirty-tenant path above.

Surfaces (the full treatment): ``util.state.lora_status()``,
``ray_tpu lora`` CLI, dashboard ``/api/lora`` + tab, lazy Prometheus
``ray_tpu_lora_adapter_{hits,misses,evictions}_total{tenant}`` +
``ray_tpu_lora_pool_utilization``, and a ``lora`` merged-timeline lane
with page_in / evict / swap instant markers. Knobs:
``RAY_TPU_LORA_POOL_SLOTS`` (adapter rows beside the null row, default
8), ``RAY_TPU_LORA_RANK_MAX`` (pool rank ceiling, default 8). The
acceptance benchmark is ``bench_serve --tenants N --tenant-zipf``.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

_POOL_SEQ = itertools.count()
_EVENTS_KEPT = 512


def default_pool_slots() -> int:
    return max(1, int(os.environ.get("RAY_TPU_LORA_POOL_SLOTS", "8")))


def default_rank_max() -> int:
    return max(1, int(os.environ.get("RAY_TPU_LORA_RANK_MAX", "8")))


def tenant_weights_name(tenant: str, prefix: str = "lora/") -> str:
    """The weight-fabric name a tenant's adapter publishes under — the
    ONE convention the pool's fabric source, the per-tenant online
    trainer, and the CLI all share."""
    return f"{prefix}{tenant}"


# ----------------------------------------------------- prometheus (lazy)
# Created on first pool construction, never at import (the weights /
# kvcache / disagg pattern — rebound ONCE to a complete dict).

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def lora_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                hits=Counter(
                    "ray_tpu_lora_adapter_hits_total",
                    "adapter-pool acquisitions served by a resident "
                    "adapter", tag_keys=("tenant",)),
                misses=Counter(
                    "ray_tpu_lora_adapter_misses_total",
                    "adapter-pool acquisitions that paged the adapter "
                    "in", tag_keys=("tenant",)),
                evictions=Counter(
                    "ray_tpu_lora_adapter_evictions_total",
                    "unpinned adapters LRU-evicted from the pool under "
                    "pressure", tag_keys=("tenant",)),
                swaps=Counter(
                    "ray_tpu_lora_adapter_swaps_total",
                    "resident adapters hot-swapped to a newer "
                    "published version", tag_keys=("tenant",)),
                utilization=Gauge(
                    "ray_tpu_lora_pool_utilization",
                    "fraction of adapter-pool rows holding a resident "
                    "adapter"))
    return _metrics


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


# -------------------------------------------------- donated row writes

_row_write_jit = None


def _row_write():
    """The ONE donated pool-row writer (lazy so importing serve.lora
    never touches jax): ``write(stack, row, leaf)`` lowers to an
    in-place O(leaf) update of ``stack[row]`` with the stack donated —
    one compiled program per stack shape, shared by every A/B leaf and
    the scale vector. Callers must hold the pool lock across the
    dispatch (see AdapterPool)."""
    global _row_write_jit
    if _row_write_jit is None:
        import functools

        import jax

        @functools.partial(jax.jit, donate_argnums=(0,))
        def write(stack, row, leaf):
            return jax.lax.dynamic_update_slice(
                stack, leaf[None], (row,) + (0,) * leaf.ndim)

        _row_write_jit = write
    return _row_write_jit


# ------------------------------------------------------- host adapters

def make_lora_adapter(config: Any, rank: int, *, seed: int = 0,
                      scale: float = 1.0) -> Dict[str, Any]:
    """A host-side adapter tree for `config`'s LoRA-target leaves:
    ``{"scale": f32[], "targets": {name: {"a": [L, in, r],
    "b": [L, r, out]}}}`` — the pytree shape the weight fabric
    publishes and the pool pages. Both A and B are random (classic
    LoRA inits B = 0 — a no-op adapter — which would make every
    isolation test vacuous), in the model's compute dtype."""
    from ray_tpu.models.generate import lora_targets

    rng = np.random.default_rng(seed)
    layers = len_blocks(config)
    targets: Dict[str, Any] = {}
    for name, d_in, d_out in lora_targets(config):
        targets[name] = {
            "a": (rng.standard_normal((layers, d_in, rank))
                  * 0.05).astype(np.float32),
            "b": (rng.standard_normal((layers, rank, d_out))
                  * 0.05).astype(np.float32),
        }
    return {"scale": np.float32(scale), "targets": targets}


def len_blocks(config: Any) -> int:
    return int(config.num_layers)


def adapter_nbytes(adapter: Dict[str, Any]) -> int:
    """Host bytes of one adapter tree (the bench's paging-amortization
    denominator)."""
    n = 0
    for ab in adapter["targets"].values():
        n += int(np.asarray(ab["a"]).nbytes)
        n += int(np.asarray(ab["b"]).nbytes)
    return n


def adapter_rank(adapter: Dict[str, Any]) -> int:
    ab = next(iter(adapter["targets"].values()))
    return int(np.asarray(ab["a"]).shape[-1])


def publish_adapter(tenant: str, adapter: Dict[str, Any], *,
                    prefix: str = "lora/", delta: bool = True) -> int:
    """Publish a tenant's adapter to the weight fabric under
    ``lora/<tenant>`` (delta publication by default — an adapter
    refresh usually touches a subset of leaves). Every subscribed
    AdapterPool sees the pubsub notice, marks the tenant dirty, and
    hot-swaps on its next acquire. Returns the committed version."""
    from ray_tpu.weights import publish

    return int(publish(adapter,
                       name=tenant_weights_name(tenant, prefix),
                       delta=delta))


# ------------------------------------------------------ adapter sources

class LocalAdapterSource:
    """Clusterless paging source: a host-side dict of adapter trees.
    ``publish()`` bumps the tenant's version and marks it dirty — the
    in-process stand-in for a weight-fabric publication (tests and the
    inline load harness use it; `fetch_delay_s` simulates a slow fetch
    so the no-stall tests can prove page-ins never block ticks)."""

    def __init__(self, adapters: Optional[Dict[str, Any]] = None, *,
                 fetch_delay_s: float = 0.0):
        self._lock = threading.Lock()
        self._adapters: Dict[str, Tuple[int, Dict[str, Any]]] = {
            t: (1, a) for t, a in (adapters or {}).items()}
        self._dirty: set = set()
        self.fetch_delay_s = float(fetch_delay_s)

    def publish(self, tenant: str, adapter: Dict[str, Any]) -> int:
        with self._lock:
            ver = self._adapters.get(tenant, (0, None))[0] + 1
            self._adapters[tenant] = (ver, adapter)
            self._dirty.add(tenant)
        return ver

    def fetch(self, tenant: str) -> Tuple[int, Dict[str, Any], int]:
        if self.fetch_delay_s > 0:
            time.sleep(self.fetch_delay_s)
        with self._lock:
            entry = self._adapters.get(tenant)
            if entry is None:
                raise KeyError(f"no adapter registered for tenant "
                               f"{tenant!r}")
            self._dirty.discard(tenant)
            ver, adapter = entry
        return ver, adapter, adapter_nbytes(adapter)

    def dirty(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._dirty


class FabricAdapterSource:
    """Weight-fabric paging source: each tenant's adapter lives under
    ``lora/<tenant>`` in the versioned registry (delta publications —
    PR 8's changed-leaves machinery — so an adapter refresh ships only
    what changed). One :class:`WeightSubscriber` per tenant, created
    lazily; the shared ``weights`` pubsub channel marks tenants dirty
    the moment a new version commits, so the next acquire hot-swaps
    without polling."""

    def __init__(self, prefix: str = "lora/"):
        self.prefix = str(prefix)
        self._lock = threading.Lock()
        self._subs: Dict[str, Any] = {}
        self._dirty: set = set()
        w = _worker()
        if w is None:
            raise RuntimeError(
                "FabricAdapterSource needs a live cluster "
                "(ray_tpu.init); use LocalAdapterSource clusterless")
        self._worker_ref = w
        w.subscribe_channel("weights", self._on_weights_msg)

    def _on_weights_msg(self, msg: Any) -> None:
        if not isinstance(msg, dict) or msg.get("kind") != "published":
            return
        name = str(msg.get("name") or "")
        if name.startswith(self.prefix):
            with self._lock:
                self._dirty.add(name[len(self.prefix):])

    def _sub(self, tenant: str):
        from ray_tpu.weights import WeightSubscriber

        with self._lock:
            sub = self._subs.get(tenant)
        if sub is not None:
            return sub
        # construct OUTSIDE the lock: the subscriber's setup talks to
        # the conductor, and holding this lock across an RPC would let
        # a slow registry stall every dirty() probe (which the pool
        # calls on its hot acquire path). Double-checked insert; a
        # racing duplicate is closed, the winner kept.
        sub = WeightSubscriber(tenant_weights_name(tenant, self.prefix))
        with self._lock:
            cur = self._subs.get(tenant)
            if cur is None:
                self._subs[tenant] = sub
                return sub
        sub.close()
        return cur

    def fetch(self, tenant: str) -> Tuple[int, Dict[str, Any], int]:
        sub = self._sub(tenant)
        with self._lock:
            self._dirty.discard(tenant)
        adapter = sub.fetch()  # numpy leaves via the producer treedef
        stats = sub.last_stats
        ver = int(stats.version) if stats else 0
        moved = int(stats.fetched_bytes) if stats else 0
        return ver, adapter, moved

    def dirty(self, tenant: str) -> bool:
        with self._lock:
            return tenant in self._dirty

    def close(self) -> None:
        try:
            self._worker_ref.unsubscribe_channel("weights",
                                                 self._on_weights_msg)
        except Exception:  # noqa: BLE001 — worker already torn down
            pass
        with self._lock:
            subs, self._subs = dict(self._subs), {}
        for sub in subs.values():
            try:
                sub.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def build_pool(config: Any, lora: Any, *, slots: Optional[int] = None,
               rank_max: Optional[int] = None,
               prefix: str = "lora/") -> Optional["AdapterPool"]:
    """The one `lora=` ctor-knob parser every replica shares
    (PrefillServer / DecodeServer / the colocated engine builders):
    ``None``/``False`` → no pool; ``True`` → page from the weight
    fabric (FabricAdapterSource); a dict of host adapter trees →
    LocalAdapterSource; an AdapterPool → used as-is (shared pool); any
    other object → treated as a source."""
    if lora is None or lora is False:
        return None
    if isinstance(lora, AdapterPool):
        return lora
    if lora is True:
        source: Any = FabricAdapterSource(prefix)
    elif isinstance(lora, dict):
        source = LocalAdapterSource(lora)
    else:
        source = lora
    return AdapterPool(config, slots=slots, rank_max=rank_max,
                       source=source)


class LoraPoolExhausted(RuntimeError):
    """Every pool row is pinned by an in-flight request — the caller
    should shed (cause `capacity`) or retry; admission control sizes
    concurrency below this in a healthy deployment."""


class _Resident:
    __slots__ = ("tenant", "row", "version", "rank", "ref", "last_used",
                 "nbytes")

    def __init__(self, tenant: str, row: int):
        self.tenant = tenant
        self.row = row
        self.version = 0
        self.rank = 0
        self.ref = 0
        self.last_used = 0
        self.nbytes = 0


class AdapterPool:
    """Refcounted LRU pool of device-resident LoRA adapters for one
    engine (or prefill server). Thread-safe; fetches run OUTSIDE the
    lock (single-flight per tenant) so a cold page-in never blocks the
    decode loop's ``tick_args`` read or another tenant's acquire."""

    def __init__(self, config: Any, *, slots: Optional[int] = None,
                 rank_max: Optional[int] = None,
                 source: Any = None,
                 pool_id: Optional[str] = None):
        import jax.numpy as jnp

        from ray_tpu.models.generate import lora_targets

        self.config = config
        self.slots = int(slots) if slots else default_pool_slots()
        self.rank_max = int(rank_max) if rank_max else default_rank_max()
        if self.slots < 1 or self.rank_max < 1:
            raise ValueError("slots and rank_max must be >= 1")
        self.source = source if source is not None \
            else LocalAdapterSource()
        self.pool_id = pool_id or f"lorapool-{os.getpid()}-" \
                                  f"{next(_POOL_SEQ)}"
        self.targets = lora_targets(config)
        self.dtype = config.dtype
        layers = len_blocks(config)
        rows = self.slots + 1  # row 0: the null/base adapter
        # the device stacks the mixed-tenant tick gathers from; zeros
        # everywhere means every row starts as the null adapter
        self._a = {name: jnp.zeros((rows, layers, d_in, self.rank_max),
                                   self.dtype)
                   for name, d_in, _ in self.targets}
        self._b = {name: jnp.zeros((rows, layers, self.rank_max, d_out),
                                   self.dtype)
                   for name, _, d_out in self.targets}
        self._scale = jnp.zeros((rows,), jnp.float32)
        self._lock = threading.Lock()
        self._by_tenant: Dict[str, _Resident] = {}
        self._free: List[int] = list(range(rows - 1, 0, -1))
        self._loading: Dict[str, threading.Event] = {}
        # last version ever installed per tenant — SURVIVES eviction.
        # A tenant evicted, republished, and paged back in arrives at a
        # DIFFERENT version than its (still-cached, version-blind)
        # namespace-keyed KV was computed under; comparing against this
        # map is what makes the swap listeners (the engine's scoped KV
        # invalidation) fire on that path too, not just on a
        # resident-row hot-swap. One int per tenant ever seen — tiny.
        self._seen_versions: Dict[str, int] = {}
        self._tick = itertools.count(1)
        self._swap_listeners: List[Callable[[str], None]] = []
        self._events: List[Dict[str, Any]] = []
        self._stats: Dict[str, int] = {k: 0 for k in (
            "acquires", "hits", "misses", "evictions", "swaps",
            "page_in_bytes", "releases")}
        self._tenant_stats: Dict[str, Dict[str, int]] = {}
        self._last_push = 0.0
        lora_metrics()  # lazy registration before the first event

    # ----------------------------------------------------------- helpers

    def add_swap_listener(self,
                          fn: Callable[[str, Optional[int]], None]
                          ) -> None:
        """Called (outside the pool lock) as ``fn(tenant,
        old_version)`` when a tenant moves to a new adapter version —
        resident hot-swap or evict→republish→re-page-in. The engine
        hooks EAGER reclamation of the old version's (version-stamped)
        KV namespace here; correctness never depends on it — a stale
        version's namespace simply stops being looked up (see
        ``cache_namespace``) and its blocks LRU out."""
        self._swap_listeners.append(fn)

    @staticmethod
    def cache_namespace(tenant: str, version: Optional[int]) -> str:
        """The prefix-cache namespace for one (tenant, adapter-version)
        pair. Stamping the VERSION into the namespace is what makes
        hot-swaps race-free by construction: a prefill that captured
        the v1 adapter commits into ``t@v1`` even if the row hot-swaps
        to v2 mid-compute, and every post-swap lookup reads ``t@v2`` —
        old-version KV can never be served under a newer adapter, with
        no ordering requirements between swaps and in-flight
        commits."""
        return f"{tenant}@v{0 if version is None else int(version)}"

    def _tenant_locked(self, tenant: str) -> Dict[str, int]:
        ts = self._tenant_stats.get(tenant)
        if ts is None:
            ts = {k: 0 for k in ("hits", "misses", "evictions",
                                 "swaps")}
            self._tenant_stats[tenant] = ts
        return ts

    def _event_locked(self, ev: Dict[str, Any]) -> None:
        ev.setdefault("ts", time.time())
        ev.setdefault("pool", self.pool_id)
        self._events.append(ev)
        if len(self._events) > _EVENTS_KEPT:
            del self._events[:len(self._events) - _EVENTS_KEPT]

    def _pad(self, arr: np.ndarray, rank_axis: int) -> np.ndarray:
        """Zero-pad an adapter leaf's rank dimension to ``rank_max`` —
        the padded columns of A (rows of B) multiply to exact-zero
        contributions, so a rank-r adapter in a rank_max pool computes
        the same delta it would at its native rank."""
        r = arr.shape[rank_axis]
        if r > self.rank_max:
            raise ValueError(
                f"adapter rank {r} exceeds the pool's rank_max "
                f"{self.rank_max} (RAY_TPU_LORA_RANK_MAX)")
        if r == self.rank_max:
            return arr
        pad = [(0, 0)] * arr.ndim
        pad[rank_axis] = (0, self.rank_max - r)
        return np.pad(arr, pad)

    def _write_row_locked(self, row: int,
                          adapter: Dict[str, Any]) -> None:
        """Write one adapter into pool row `row` through the DONATED
        row writer — an in-place O(row) update per leaf, never an
        O(pool) stack copy (the ROADMAP's 64-slot x 32-layer scale
        bug). Caller holds the pool lock: every stack read (the tick's
        ``dispatch_tick``, the prefill merge's ``adapter_slice``)
        dispatches under the same lock, so the donation can never
        invalidate an array a concurrent reader is about to hand to
        XLA — same-device stream order serializes the rest."""
        import jax.numpy as jnp

        write = _row_write()
        rw = np.int32(row)
        layers = len_blocks(self.config)
        for name, d_in, d_out in self.targets:
            a = self._pad(np.asarray(adapter["targets"][name]["a"]), 2)
            b = self._pad(np.asarray(adapter["targets"][name]["b"]), 1)
            if a.shape != (layers, d_in, self.rank_max) \
                    or b.shape != (layers, self.rank_max, d_out):
                raise ValueError(
                    f"adapter leaf {name!r} shaped a={a.shape} "
                    f"b={b.shape} does not fit this model's target "
                    f"({layers}, {d_in}->{d_out})")
            self._a[name] = write(self._a[name], rw,
                                  jnp.asarray(a, self.dtype))
            self._b[name] = write(self._b[name], rw,
                                  jnp.asarray(b, self.dtype))
        # ravel()[0]: the fabric's 0-d -> 1-d chunk promotion may hand
        # the scale back as a [1] array
        self._scale = write(
            self._scale, rw,
            jnp.asarray(float(np.asarray(adapter.get("scale", 1.0))
                              .ravel()[0]), jnp.float32))

    # ------------------------------------------------------------ paging

    def acquire(self, tenant: str) -> int:
        """Pin `tenant`'s adapter and return its pool row (the per-slot
        index the decode tick gathers by). Hit: resident and current —
        bump the pin. Miss: page in (fetch outside the lock,
        single-flight per tenant), evicting the LRU unpinned row when
        the pool is full. Dirty (a newer version was published):
        re-fetch and hot-swap the SAME row — other tenants' rows are
        untouched. Raises LoraPoolExhausted when every row is pinned."""
        tenant = str(tenant)
        while True:
            # the dirty probe runs OUTSIDE the pool lock: tick_args()
            # blocks on that lock, and a source implementation may take
            # its own lock here — nesting them would let a slow source
            # transitively stall the decode loop. Non-atomic is fine: a
            # publish landing between this check and the return is
            # caught by the tenant's next acquire.
            dirty = self.source.dirty(tenant)
            with self._lock:
                r = self._by_tenant.get(tenant)
                if r is not None and not dirty:
                    r.ref += 1
                    r.last_used = next(self._tick)
                    self._stats["acquires"] += 1
                    self._stats["hits"] += 1
                    self._tenant_locked(tenant)["hits"] += 1
                    lora_metrics()["hits"].inc(tags={"tenant": tenant})
                    return r.row
                loading = self._loading.get(tenant)
                if loading is None:
                    self._loading[tenant] = threading.Event()
                    break
            # another thread is paging this tenant in: wait, re-check
            loading.wait(timeout=120.0)
        try:
            version, adapter, moved = self.source.fetch(tenant)
            row, prev_version, evicted = self._install(tenant, version,
                                                       adapter, moved)
        finally:
            with self._lock:
                ev = self._loading.pop(tenant, None)
            if ev is not None:
                ev.set()
        if prev_version is not None:
            self._fire_swap_listeners(tenant, prev_version)
        self.publish_telemetry()
        return row

    def _fire_swap_listeners(self, tenant: str,
                             old_version: int) -> None:
        for fn in self._swap_listeners:
            try:
                fn(tenant, old_version)
            except Exception:  # noqa: BLE001 — listener's problem
                pass

    def _install(self, tenant: str, version: int,
                 adapter: Dict[str, Any], moved: int
                 ) -> Tuple[int, Optional[int], Optional[str]]:
        """Returns ``(row, superseded_version, evicted_tenant)``.
        `superseded_version` is the tenant's previous adapter version
        when this install moved it to a NEW one (resident hot-swap OR
        evict→republish→re-page-in) — the caller fires the swap
        listeners with it so the old version's KV namespace gets
        eagerly reclaimed; None when nothing was superseded."""
        rank = adapter_rank(adapter)
        nbytes = adapter_nbytes(adapter)
        with self._lock:
            now = next(self._tick)
            r = self._by_tenant.get(tenant)
            swapped = r is not None
            prev_version = self._seen_versions.get(tenant)
            superseded = (prev_version
                          if prev_version is not None
                          and prev_version != int(version) else None)
            evicted: Optional[str] = None
            if r is None:
                if self._free:
                    row = self._free.pop()
                else:
                    victim = min(
                        (c for c in self._by_tenant.values()
                         if c.ref == 0),
                        key=lambda c: c.last_used, default=None)
                    if victim is None:
                        raise LoraPoolExhausted(
                            f"adapter pool {self.pool_id}: all "
                            f"{self.slots} rows pinned by in-flight "
                            f"requests (RAY_TPU_LORA_POOL_SLOTS)")
                    evicted = victim.tenant
                    del self._by_tenant[victim.tenant]
                    row = victim.row
                    self._stats["evictions"] += 1
                    self._tenant_locked(evicted)["evictions"] += 1
                    self._event_locked({"kind": "evict",
                                        "tenant": evicted,
                                        "row": row})
                r = _Resident(tenant, row)
                self._by_tenant[tenant] = r
            # the DONATED write dispatches under the lock — the same
            # lock every stack read dispatches under, so stream order
            # makes the in-place update tick-safe
            self._write_row_locked(r.row, adapter)
            r.version = int(version)
            r.rank = rank
            r.nbytes = nbytes
            r.last_used = now
            r.ref += 1
            self._seen_versions[tenant] = int(version)
            self._stats["acquires"] += 1
            if swapped:
                self._stats["swaps"] += 1
                self._tenant_locked(tenant)["swaps"] += 1
                self._event_locked({"kind": "swap", "tenant": tenant,
                                    "row": r.row, "version": version})
            else:
                self._stats["misses"] += 1
                self._tenant_locked(tenant)["misses"] += 1
                self._event_locked({"kind": "page_in", "tenant": tenant,
                                    "row": r.row, "version": version,
                                    "bytes": moved or nbytes,
                                    "superseded": superseded})
            self._stats["page_in_bytes"] += moved or nbytes
            row = r.row
            util = len(self._by_tenant) / self.slots
        m = lora_metrics()
        if swapped:
            m["swaps"].inc(tags={"tenant": tenant})
        else:
            m["misses"].inc(tags={"tenant": tenant})
        if evicted is not None:
            m["evictions"].inc(tags={"tenant": evicted})
        m["utilization"].set(util)
        return row, superseded, evicted

    def release(self, tenant: str) -> None:
        """Drop one pin. Refcount-0 adapters STAY resident (that is the
        cache) and are reclaimed only by LRU eviction under pressure —
        the kvcache refcount discipline."""
        with self._lock:
            r = self._by_tenant.get(str(tenant))
            if r is not None and r.ref > 0:
                r.ref -= 1
            self._stats["releases"] += 1

    def refresh(self, tenant: str) -> bool:
        """Hot-swap `tenant`'s resident adapter to the newest published
        version NOW (the publish path's dirty flag does this lazily on
        the next acquire; tests and operators force it). No-op when the
        tenant is not resident. Existing pins keep counting — the swap
        changes the row's CONTENT between ticks, never its identity."""
        tenant = str(tenant)
        with self._lock:
            if tenant not in self._by_tenant:
                return False
        version, adapter, moved = self.source.fetch(tenant)
        with self._lock:
            r = self._by_tenant.get(tenant)
            if r is None or r.version == int(version):
                return False
            old_version = r.version
            self._write_row_locked(r.row, adapter)
            r.version = int(version)
            r.rank = adapter_rank(adapter)
            r.nbytes = adapter_nbytes(adapter)
            self._seen_versions[tenant] = int(version)
            self._stats["swaps"] += 1
            self._stats["page_in_bytes"] += moved or r.nbytes
            self._tenant_locked(tenant)["swaps"] += 1
            self._event_locked({"kind": "swap", "tenant": tenant,
                                "row": r.row, "version": version})
        lora_metrics()["swaps"].inc(tags={"tenant": tenant})
        self._fire_swap_listeners(tenant, old_version)
        self.publish_telemetry()
        return True

    # -------------------------------------------------------- device API

    def _tick_args_locked(self, slot_adapter: np.ndarray
                          ) -> Dict[str, Any]:
        import jax.numpy as jnp

        out: Dict[str, Any] = {
            "idx": jnp.asarray(slot_adapter, jnp.int32),
            "scale": self._scale,
        }
        for name, _, _ in self.targets:
            out[name] = (self._a[name], self._b[name])
        return out

    def dispatch_tick(self, fn: Callable[[Dict[str, Any]], Any],
                      slot_adapter: np.ndarray) -> Any:
        """Build the mixed-tenant tick's `lora` argument (per-slot pool
        rows + the stacks, models/llama.py ``llama_decode(lora=)``
        layout) and dispatch ``fn(args)`` UNDER the pool lock. Pool-row
        writes are donated jits dispatched under this same lock, so a
        page-in racing a tick can never donate away an array the tick
        is about to hand to XLA — dispatch is the only critical
        section (the kvcache gather/commit discipline); the tick's
        compute still overlaps page-in fetches freely."""
        with self._lock:
            return fn(self._tick_args_locked(slot_adapter))

    def tick_args(self, slot_adapter: np.ndarray) -> Dict[str, Any]:
        """Snapshot of the tick argument for INSPECTION (tests,
        debugging). Dispatching a jit on these references outside
        ``dispatch_tick`` races the donated row writes — the engine
        always goes through ``dispatch_tick``."""
        with self._lock:
            return self._tick_args_locked(slot_adapter)

    def adapter_slice(self, row: int, with_version: bool = False):
        """ONE adapter's device arrays (for the single-tenant prefill
        merge): ``{"scale", "targets": {name: {"a": [L,in,r_max],
        "b": [L,r_max,out]}}}``. With ``with_version`` also returns
        the row's resident adapter version, read under the SAME lock
        as the arrays — the pair the versioned cache namespace needs
        (a swap landing between a separate read and the slice would
        stamp v1 KV with v2's namespace)."""
        with self._lock:
            sl = {
                "scale": self._scale[row],
                "targets": {name: {"a": self._a[name][row],
                                   "b": self._b[name][row]}
                            for name, _, _ in self.targets},
            }
            if not with_version:
                return sl
            version = next((r.version
                            for r in self._by_tenant.values()
                            if r.row == row), None)
            return sl, version

    def resident_version(self, tenant: str) -> Optional[int]:
        with self._lock:
            r = self._by_tenant.get(str(tenant))
            return None if r is None else r.version

    # -------------------------------------------------- stats / telemetry

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            residents = {t: {"row": r.row, "version": r.version,
                             "rank": r.rank, "ref": r.ref,
                             "nbytes": r.nbytes}
                         for t, r in self._by_tenant.items()}
            s.update(
                role="pool",
                pool_id=self.pool_id,
                slots=self.slots,
                rank_max=self.rank_max,
                resident=len(residents),
                pinned=sum(1 for r in self._by_tenant.values()
                           if r.ref > 0),
                utilization=len(residents) / self.slots,
                residents=residents,
                tenants={t: dict(v)
                         for t, v in self._tenant_stats.items()},
            )
        acq = s["acquires"]
        s["hit_rate"] = s["hits"] / acq if acq else 0.0
        return s

    def drain_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
        return out

    def publish_telemetry(self, force: bool = False) -> None:
        """Best-effort push of pool stats + pending timeline events to
        the conductor (no-op without a live cluster); throttled unless
        forced — the one-set-of-numbers source for every lora
        surface."""
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        w = _worker()
        if w is None:
            self.drain_events()  # keep the buffer bounded
            return
        try:
            w.conductor.notify("report_lora_stats", w.worker_id,
                               self.pool_id, self.stats())
            for ev in self.drain_events():
                w.conductor.notify("report_lora_event", ev)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass


__all__ = ["AdapterPool", "FabricAdapterSource", "LocalAdapterSource",
           "LoraPoolExhausted", "adapter_nbytes", "adapter_rank",
           "build_pool", "default_pool_slots", "default_rank_max",
           "lora_metrics", "make_lora_adapter", "publish_adapter",
           "tenant_weights_name"]
