"""Per-request context — analog of the reference's
python/ray/serve/context.py (_serve_request_context contextvar)."""
from __future__ import annotations

import contextvars
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class RequestContext:
    route: str = ""
    request_id: str = ""
    app_name: str = ""
    multiplexed_model_id: str = ""
    headers: Dict[str, str] = field(default_factory=dict)


_request_context: contextvars.ContextVar[RequestContext] = \
    contextvars.ContextVar("serve_request_context", default=RequestContext())


def get_request_context() -> RequestContext:
    return _request_context.get()


def set_request_context(ctx: RequestContext):
    return _request_context.set(ctx)
