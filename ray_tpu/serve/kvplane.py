"""Global KV plane — the tiered prefix cache (HBM -> host RAM -> object
store) with a cluster-wide prefix directory.

int8 KV blocks doubled a single replica's prefix pool; this subsystem
adds the next multiplier, hierarchy: cache residency stops being
bounded by one replica's HBM.

- **Tier 1** is the engine's paged HBM pool (models/kvcache.py),
  unchanged.
- **Tier 2** (``HostArena``) is a bounded per-replica host-RAM arena.
  A block evicted from the HBM pool under pressure spills its
  int8+per-block-channel-scales wire-format payload (``_write_block_q``'s
  layout) here instead of dying; a later lookup whose chain walk breaks
  re-adopts the block through the pool's normal insert path. LRU within
  the arena, byte-bounded (``RAY_TPU_KVPLANE_ARENA_BYTES``). int8 pools
  round-trip bit-exactly; fp pools re-enter within the int8 tolerance
  contract.
- **Tier 3** persists cold hot-prompt prefixes as ``util/chunks``
  objects ANY replica can adopt, with a conductor-side **prefix
  directory**: digest-chain -> holder + descriptor, namespaced by
  tenant/adapter version, the same metadata-only atomic-commit registry
  pattern as the weight fabric, TTL-reaped
  (``RAY_TPU_KVPLANE_T3_TTL_S``) and keep-last-K GC'd. The
  ``DisaggRouter``'s prefix-affinity routing upgrades from "hash to the
  replica that PROBABLY has it" to "look up who HAS it, or fetch it
  over the transfer plane" — a directory miss falls back to the
  affinity hash bit-identically (``RAY_TPU_KVPLANE_DIRECTORY=0`` turns
  the lookup off wholesale).

Correctness invariant (asserted in tests/test_kvplane.py): with int8
pools a block's spill/readopt round trip through ANY tier is
byte-for-byte the pool bytes that were evicted, so engine outputs with
the KV plane enabled are bit-identical to the single-tier engine. The
namespace scoping of the hash chains carries through every tier — one
tenant's spilled or published KV can never match another tenant's
prompt, because the digests themselves are namespace-rooted.

Surfaces (the full treatment every subsystem gets):
``util.state.kvplane_status()``, CLI ``ray_tpu kvplane [--json
--events]``, dashboard ``/api/kvplane`` + SPA tab, the lazy
``ray_tpu_kvplane_*`` Prometheus family (per-tier hits / evictions /
spills / fetched bytes / reused tokens), ``kvplane`` markers in the
merged timeline (spill / tier2_hit / tier3_publish / tier3_adopt /
directory_hit), and per-request flight-recorder phases
``kvplane_tier2_fetch`` / ``kvplane_tier3_fetch`` so p99 attribution
can name the KV plane.

Knobs (all read through util/envknobs): ``RAY_TPU_KVPLANE`` (master
enable, default 1), ``RAY_TPU_KVPLANE_ARENA_BYTES`` (tier-2 bound,
default 128 MiB), ``RAY_TPU_KVPLANE_DIRECTORY`` (directory lookups +
tier-3 publication, default 1), ``RAY_TPU_KVPLANE_T3_TTL_S`` (directory
entry TTL, default 600), ``RAY_TPU_KVPLANE_T3_MIN_BLOCKS`` (smallest
prefix worth publishing, default 2).
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_EVENTS_KEPT = 512


# ------------------------------------------------------------ env knobs

def kvplane_enabled() -> bool:
    """Master enable — gates the arena attach AND tier-3 publication."""
    from ray_tpu.util import envknobs

    return envknobs.get_str("RAY_TPU_KVPLANE", "1") == "1"


def arena_bytes_default() -> int:
    """Tier-2 host-arena byte bound (``RAY_TPU_KVPLANE_ARENA_BYTES``)."""
    from ray_tpu.util import envknobs

    return envknobs.get_int("RAY_TPU_KVPLANE_ARENA_BYTES", 128 << 20)


def directory_enabled() -> bool:
    """Prefix-directory lookups + tier-3 publication
    (``RAY_TPU_KVPLANE_DIRECTORY``) — off falls back to the affinity
    hash bit-identically."""
    from ray_tpu.util import envknobs

    return envknobs.get_str("RAY_TPU_KVPLANE_DIRECTORY", "1") == "1"


def t3_ttl_s() -> float:
    """Directory-entry TTL (``RAY_TPU_KVPLANE_T3_TTL_S``) the conductor
    reaper enforces; 0 disables the age check."""
    from ray_tpu.util import envknobs

    return envknobs.get_float("RAY_TPU_KVPLANE_T3_TTL_S", 600.0)


def t3_min_blocks() -> int:
    """Smallest full-block prefix worth publishing to tier 3
    (``RAY_TPU_KVPLANE_T3_MIN_BLOCKS``)."""
    from ray_tpu.util import envknobs

    return envknobs.get_int("RAY_TPU_KVPLANE_T3_MIN_BLOCKS", 2)


# ----------------------------------------------------- prometheus (lazy)
# Created on first arena construction / directory use, never at import
# (the kvcache_metrics pattern — rebound ONCE to a complete dict).

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def kvplane_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                hits=Counter(
                    "ray_tpu_kvplane_hits_total",
                    "prefix blocks re-adopted from a lower tier",
                    tag_keys=("tier",)),
                spills=Counter(
                    "ray_tpu_kvplane_spills_total",
                    "HBM-evicted blocks spilled into the tier-2 host "
                    "arena instead of dying"),
                evictions=Counter(
                    "ray_tpu_kvplane_evictions_total",
                    "blocks dropped OUT of a kvplane tier (arena LRU, "
                    "directory TTL/GC)",
                    tag_keys=("tier",)),
                fetched_bytes=Counter(
                    "ray_tpu_kvplane_fetched_bytes_total",
                    "wire-format bytes pulled back out of a tier on a "
                    "hit",
                    tag_keys=("tier",)),
                reused_tokens=Counter(
                    "ray_tpu_kvplane_reused_tokens_total",
                    "prompt tokens whose prefill was recovered from a "
                    "kvplane tier",
                    tag_keys=("tier",)),
                directory=Counter(
                    "ray_tpu_kvplane_directory_total",
                    "prefix-directory routing decisions",
                    tag_keys=("outcome",)),
                arena_bytes=Gauge(
                    "ray_tpu_kvplane_arena_bytes",
                    "tier-2 host-arena resident bytes"))
    return _metrics


# ------------------------------------------------------------- tier 2

class HostArena:
    """Bounded host-RAM spill arena for one replica's HBM pool (tier 2).

    Keys ARE the pool's index keys — ``("full", digest)`` /
    ``("partial", parent_digest, tokens)`` — with the digests already
    namespace-rooted, so tenant isolation is inherited, not re-checked.
    ``take_*`` POPS (a hit moves the block back to tier 1; no double
    residency). LRU within the byte bound. Thread-safe: accept() is
    called under the pool lock, stats()/drain_events() from telemetry
    threads."""

    def __init__(self, max_bytes: Optional[int] = None,
                 replica: Optional[str] = None):
        self.max_bytes = int(arena_bytes_default()
                             if max_bytes is None else max_bytes)
        self.replica = replica
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, Dict[str, Any]]" \
            = OrderedDict()
        # parent_digest -> {tokens: key} for the partial-tail probe
        self._partials: Dict[bytes, Dict[Tuple[int, ...], tuple]] = {}
        self._bytes = 0
        self._events: List[Dict[str, Any]] = []
        self._stats: Dict[str, int] = {
            k: 0 for k in ("spills", "spill_bytes", "tier2_hits",
                           "tier2_probes", "tier2_reused_tokens",
                           "tier2_fetched_bytes", "arena_evictions")}
        self._tl = threading.local()
        kvplane_metrics()  # lazy registration, before the first event

    @staticmethod
    def _payload_bytes(p: Dict[str, Any]) -> int:
        return int(p["qk"].nbytes + p["qv"].nbytes
                   + p["sk"].nbytes + p["sv"].nbytes)

    def _event_locked(self, ev: Dict[str, Any]) -> None:
        ev.setdefault("ts", time.time())
        if self.replica is not None:
            ev.setdefault("replica", self.replica)
        self._events.append(ev)
        if len(self._events) > _EVENTS_KEPT:
            del self._events[:len(self._events) - _EVENTS_KEPT]

    def _insert_locked(self, key: tuple, payload: Dict[str, Any],
                       size: int) -> None:
        self._entries[key] = payload
        self._entries.move_to_end(key)
        self._bytes += size
        if key[0] == "partial":
            self._partials.setdefault(key[1], {})[key[2]] = key
        while self._bytes > self.max_bytes and self._entries:
            old_key, old = self._entries.popitem(last=False)
            self._bytes -= self._payload_bytes(old)
            self._drop_partial_locked(old_key)
            self._stats["arena_evictions"] += 1
            kvplane_metrics()["evictions"].inc(tags={"tier": "2"})

    def _drop_partial_locked(self, key: tuple) -> None:
        if key[0] != "partial":
            return
        by_tok = self._partials.get(key[1])
        if by_tok is not None:
            by_tok.pop(key[2], None)
            if not by_tok:
                del self._partials[key[1]]

    def accept(self, payload: Dict[str, Any]) -> None:
        """Spill sink — an HBM eviction's wire-format payload enters
        the arena (refreshing recency if the identity already lives
        here). Called under the pool lock: dict work only."""
        key = payload.get("index_key")
        if key is None:
            return
        size = self._payload_bytes(payload)
        if size > self.max_bytes:
            return  # a block bigger than the arena can never fit
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= self._payload_bytes(old)
                self._drop_partial_locked(key)
            self._insert_locked(key, payload, size)
            self._stats["spills"] += 1
            self._stats["spill_bytes"] += size
            self._event_locked({"kind": "spill",
                                "block_tokens": payload.get("filled"),
                                "nbytes": size,
                                "namespace": payload.get("ns")})
        m = kvplane_metrics()
        m["spills"].inc()
        m["arena_bytes"].set(self._bytes)

    def give_back(self, payload: Dict[str, Any]) -> None:
        """Return a popped payload whose re-adoption failed (pool had
        no allocatable block) — not a new spill, no counters."""
        key = payload.get("index_key")
        if key is None:
            return
        with self._lock:
            if key not in self._entries:
                self._insert_locked(key, payload,
                                    self._payload_bytes(payload))

    def _hit_locked(self, key: tuple, payload: Dict[str, Any],
                    t0: float) -> Dict[str, Any]:
        size = self._payload_bytes(payload)
        self._bytes -= size
        self._drop_partial_locked(key)
        self._stats["tier2_hits"] += 1
        self._stats["tier2_reused_tokens"] += int(payload["filled"])
        self._stats["tier2_fetched_bytes"] += size
        self._event_locked({"kind": "tier2_hit",
                            "block_tokens": payload.get("filled"),
                            "nbytes": size,
                            "namespace": payload.get("ns")})
        acc = getattr(self._tl, "acc", None)
        if acc is not None:
            acc["blocks"] += 1
            acc["tokens"] += int(payload["filled"])
            acc["nbytes"] += size
            acc["ms"] += (time.perf_counter() - t0) * 1e3
        m = kvplane_metrics()
        m["hits"].inc(tags={"tier": "2"})
        m["reused_tokens"].inc(int(payload["filled"]), tags={"tier": "2"})
        m["fetched_bytes"].inc(size, tags={"tier": "2"})
        m["arena_bytes"].set(self._bytes)
        return payload

    def take_full(self, digest: bytes,
                  blk_tokens: Tuple[int, ...]) -> Optional[Dict[str, Any]]:
        """Pop the full block keyed by `digest` iff its exact token
        tuple matches (a digest collision must never re-adopt wrong
        KV). Called under the pool lock from the lookup chain walk."""
        t0 = time.perf_counter()
        key = ("full", digest)
        with self._lock:
            self._stats["tier2_probes"] += 1
            payload = self._entries.get(key)
            if payload is None or payload["tokens"] != blk_tokens:
                return None
            del self._entries[key]
            return self._hit_locked(key, payload, t0)

    def take_partial(self, digest: bytes, remainder,
                     budget: int) -> Optional[Dict[str, Any]]:
        """Pop the LONGEST spilled partial tail under `digest` whose
        tokens prefix-match `remainder` within `budget` tokens."""
        t0 = time.perf_counter()
        rem = tuple(int(t) for t in np.asarray(remainder).reshape(-1))
        with self._lock:
            self._stats["tier2_probes"] += 1
            best_key: Optional[tuple] = None
            best_len = 0
            for ptoks, key in self._partials.get(digest, {}).items():
                k = len(ptoks)
                if (k > best_len and k <= budget
                        and rem[:k] == ptoks):
                    best_key, best_len = key, k
            if best_key is None:
                return None
            payload = self._entries.pop(best_key)
            return self._hit_locked(best_key, payload, t0)

    # --------------------------------------- per-request accounting
    # The arena is hit from inside PagedKVCache.lookup(), deep under
    # the engine — a thread-local accumulator lets the replica bracket
    # one request's prefill and attribute its tier-2 traffic to the
    # flight recorder (each actor request runs on its own thread).

    def begin_request(self) -> None:
        self._tl.acc = {"blocks": 0, "tokens": 0, "nbytes": 0,
                        "ms": 0.0}

    def end_request(self) -> Dict[str, Any]:
        acc = getattr(self._tl, "acc", None) \
            or {"blocks": 0, "tokens": 0, "nbytes": 0, "ms": 0.0}
        self._tl.acc = None
        return acc

    # ------------------------------------------------ stats / events

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            s.update(entries=len(self._entries), bytes=self._bytes,
                     max_bytes=self.max_bytes)
        probes = s["tier2_probes"]
        s["tier2_hit_rate"] = (s["tier2_hits"] / probes
                               if probes else 0.0)
        return s

    def drain_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._events = self._events, []
        return out


# ------------------------------------------------------------- tier 3

def prefix_digests(tokens, block_size: int,
                   namespace: Optional[str] = None,
                   max_blocks: int = 32) -> List[str]:
    """Directory keys for a prompt — re-exported from models/kvcache so
    router code needs no kvcache import."""
    from ray_tpu.models import kvcache

    return kvcache.prefix_digests(tokens, block_size, namespace,
                                  max_blocks)


def directory_lookup(worker, namespace: Optional[str], tokens,
                     block_size: int,
                     max_blocks: int = 32) -> Optional[Dict[str, Any]]:
    """Ask the conductor's prefix directory who HOLDS the longest
    published prefix of `tokens`. Returns the directory entry (holder,
    descriptor, matched digest) or None — every failure path is a None,
    so a directory outage degrades to the affinity hash, never to an
    error."""
    digests = prefix_digests(tokens, block_size, namespace, max_blocks)
    if not digests:
        return None
    try:
        entry = worker.conductor.call("kvplane_lookup",
                                      namespace or "", digests,
                                      timeout=5.0)
    except Exception:  # noqa: BLE001 — pre-kvplane conductor / outage
        return None
    if not isinstance(entry, dict) or entry.get("error"):
        return None
    return entry


def publish_prefix(worker, cache, tokens, namespace: Optional[str],
                   holder: str, machine: Optional[str] = None,
                   min_blocks: Optional[int] = None,
                   max_blocks: int = 32) -> Optional[Tuple[str, list]]:
    """Persist the longest cached full-block prefix of `tokens` as
    chunk-fabric objects and commit it to the conductor's prefix
    directory (metadata only — the atomic-commit registry pattern).
    Returns ``(digest_hex, refs)`` — the caller OWNS the refs, they are
    the object lifetime — or None when nothing was published."""
    from ray_tpu.util import chunks

    mb = t3_min_blocks() if min_blocks is None else int(min_blocks)
    out = cache.export_prefix(tokens, namespace, max_blocks)
    if out is None:
        return None
    packed, n_tokens, digest_hex = out
    if n_tokens < mb * cache.block_size:
        return None
    refs, desc = chunks.put_tree(worker, packed)
    meta = {"desc": desc, "holder": holder, "machine": machine,
            "tokens": int(n_tokens),
            "nbytes": int(desc.get("total_bytes", 0)),
            "namespace": namespace}
    # the directory commit is the REGISTRATION step shardlint's
    # unregistered-prefix-publish rule checks for
    res = worker.conductor.call("kvplane_publish", namespace or "",
                                digest_hex, meta, timeout=10.0)
    if not isinstance(res, dict) or res.get("error") \
            or res.get("status") == "already":
        return None  # refs die here; the existing holder keeps serving
    return digest_hex, refs


def fetch_and_adopt(worker, cache, entry: Dict[str, Any], tokens,
                    namespace: Optional[str]) -> Tuple[int, Dict[str, Any]]:
    """Pull a directory entry's tier-3 object over the transfer plane
    and adopt it into `cache`. Returns ``(blocks_adopted,
    fetcher_stats)`` — 0 blocks on any fetch failure (the caller just
    prefills from scratch; tier 3 is an accelerator, not a
    dependency)."""
    from ray_tpu.util import chunks

    fetcher = chunks.ChunkFetcher(worker, caller="kvplane")
    try:
        packed = chunks.fetch_tree(worker, entry["desc"],
                                   fetcher=fetcher)
    except Exception:  # noqa: BLE001 — holder died, refs reaped, ...
        return 0, fetcher.stats()
    adopted = cache.import_prefix(tokens, packed, namespace)
    st = fetcher.stats()
    if adopted:
        m = kvplane_metrics()
        m["hits"].inc(tags={"tier": "3"})
        m["reused_tokens"].inc(adopted * cache.block_size,
                               tags={"tier": "3"})
        m["fetched_bytes"].inc(int(st.get("fetched_bytes", 0)),
                               tags={"tier": "3"})
    return adopted, st
