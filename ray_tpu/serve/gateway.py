"""OpenAI-compatible HTTP front door for the serving plane.

An asyncio ingress tier (aiohttp server on its own thread + event
loop, the serve/proxy.py idiom) that speaks REAL sockets — so slow
clients, dropped connections, and mixed traffic classes exercise
genuine backpressure — and bridges onto the blocking
``DisaggRouter.generate`` data plane through an executor pool plus the
router's ``on_tokens`` chunk callback (the chunked-pull decode stream,
re-framed as SSE).

Routes::

    POST /v1/completions        OpenAI text completion (+ SSE stream)
    POST /v1/chat/completions   OpenAI chat completion (+ SSE stream)
    GET  /v1/models             the model -> router table
    GET  /-/healthz             liveness
    GET  /-/gateway             this replica's stats snapshot (JSON)

Request contract:

- ``Authorization: Bearer <key>`` resolves the tenant through the
  QoS gate's API-key table (serve/qos.py); ``X-Tenant`` (or OpenAI's
  ``user`` field) is the keyless fallback.
- ``priority`` body field / ``X-Priority`` header picks the class
  (``interactive`` | ``batch``); interactive requests may PREEMPT a
  batch-tier decode slot (router cancel + replay-with-history — the
  resumed stream is bit-identical, same oracle as failover).
- ``X-Request-Deadline: <seconds>`` maps onto
  ``generate(deadline_s=)`` so mid-stream deadline sheds attribute
  correctly for HTTP-originated requests.
- Over-quota / rate-limited -> 429 with ``Retry-After`` (from
  RequestShedError.retry_after_s); capacity/deadline/failover sheds
  -> 503 with ``Retry-After`` + ``X-Shed-Cause``.
- A client that disconnects mid-stream is REAPED: the handler's
  cancel event sheds the router request (cause ``disconnect``) and
  the engine slot frees at the next tick boundary instead of
  decoding to an abandoned socket.

The tiny research checkpoints ship no tokenizer, so the default
:class:`ByteCodec` folds utf-8 bytes into the model vocab on encode
and renders token ids as space-joined integers on decode — every
surface stays bit-checkable against the engine oracle. ``prompt`` may
also be a raw token-id list (the OpenAI array-of-tokens form), which
is what bench_serve --http and the tests drive.

Per repo convention the gateway gets the full surface treatment:
``util.state.gateway_status()``, ``ray_tpu gateway``, dashboard
``/api/gateway`` + tab, lazy Prometheus
(``ray_tpu_gateway_requests_total{route,class,code}``,
``ray_tpu_gateway_ttft_ms{class}``,
``ray_tpu_gateway_rate_limited_total{tenant}``,
``ray_tpu_gateway_preemptions_total``), and the merged timeline's
``gateway`` lane (accept / first_byte / preempt / rate_limit /
disconnect markers) — one set of numbers across all five.
"""
from __future__ import annotations

import asyncio
import itertools
import json
import os
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

from ray_tpu.observability import requests as reqtrace

from .autoscale import SlidingWindow
from .handle import RequestShedError
from .qos import (CLASSES, INTERACTIVE, QosGate, gateway_metrics,
                  push_gateway_event, push_gateway_stats, shed_outcome)

_GW_SEQ = itertools.count()

# write failures that mean "the client went away", not "we broke"
_CLIENT_GONE = (ConnectionResetError, ConnectionAbortedError,
                BrokenPipeError)


class ByteCodec:
    """Deterministic toy text codec for tokenizer-less checkpoints:
    encode folds utf-8 bytes into ``[1, vocab)`` (id 0 is reserved —
    many configs use it for padding), decode renders ids as
    space-joined integers. decode(encode(s)) is NOT the identity —
    the contract is determinism and prefix-stability (the streaming
    deltas concatenate to exactly the non-streaming body), not
    round-tripping."""

    def __init__(self, vocab_size: int = 32000):
        self.vocab_size = max(3, int(vocab_size))

    def encode(self, text: str) -> List[int]:
        data = text.encode("utf-8")
        span = self.vocab_size - 1
        return [1 + (b % span) for b in data] or [1]

    def decode(self, tokens) -> str:
        return " ".join(str(int(t)) for t in tokens)


def _sse_frame(payload: Any) -> bytes:
    """One SSE data frame. Payloads are single-line JSON (json.dumps
    emits no raw newlines), so the one-line form is spec-compliant."""
    if isinstance(payload, bytes):
        data = payload
    elif isinstance(payload, str):
        data = payload.encode()
    else:
        data = json.dumps(payload, default=str).encode()
    return b"data: " + data + b"\n\n"


class GatewayServer:
    """One gateway replica: an aiohttp server thread in front of one
    (or several, keyed by model name) DisaggRouter(s). Runs equally
    as an in-process object or a ray_tpu actor — the constructor only
    spawns a thread; ``ready()`` blocks until the socket is bound."""

    def __init__(self, router: Any = None, *,
                 models: Optional[Dict[str, Any]] = None,
                 model: str = "ray-tpu",
                 qos: Optional[QosGate] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 eos_token: Optional[int] = None,
                 vocab_size: int = 32000,
                 codec: Any = None,
                 default_max_tokens: int = 16,
                 max_tokens_cap: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 request_timeout_s: float = 120.0,
                 chaos_spec: Optional[str] = None,
                 replica: int = 0,
                 gateway_id: Optional[str] = None):
        if models is None:
            if router is None:
                raise ValueError("need a router (or a models= table)")
            models = {model: router}
        self._models = dict(models)
        self._qos = qos
        self._host = host
        self._port = port
        self._eos_token = eos_token
        self._codec = codec or ByteCodec(vocab_size)
        self.default_max_tokens = int(default_max_tokens)
        if max_tokens_cap is None:
            max_tokens_cap = int(os.environ.get(
                "RAY_TPU_GATEWAY_MAX_TOKENS", "512"))
        self.max_tokens_cap = max(1, int(max_tokens_cap))
        self.default_deadline_s = default_deadline_s
        self.request_timeout_s = float(request_timeout_s)
        self.gateway_id = gateway_id or \
            f"gateway-{os.getpid()}-{next(_GW_SEQ)}"
        # scripted connection drops (resilience/chaos.py
        # drop_connection at=token:K): the monkey's exit_fn latches a
        # flag instead of killing the process; the handler that
        # crossed the K-th served token aborts ITS transport — from
        # the router's point of view this is exactly a client that
        # vanished, which is the point: the chaos knob proves the
        # disconnect-reap path with a deterministic trigger.
        from ray_tpu.resilience.chaos import serve_monkey_from_spec

        self._chaos = serve_monkey_from_spec(
            chaos_spec, "gateway", replica, exit_fn=self._chaos_fire)
        self._chaos_fired = False
        self._lock = threading.Lock()
        self._stats: Dict[str, Any] = {
            "accepted": 0, "completed": 0, "streamed": 0,
            "disconnects": 0, "rate_limited": 0, "sheds": 0,
            "errors": 0, "preempt_dropped": 0, "tokens_out": 0,
        }
        self._by_class: Dict[str, Dict[str, int]] = {
            c: {"accepted": 0, "completed": 0, "shed": 0,
                "disconnects": 0} for c in CLASSES}
        self._by_code: Dict[str, int] = {}
        self._ttft_win: Dict[str, SlidingWindow] = {
            c: SlidingWindow() for c in CLASSES}
        self._last_push = 0.0
        self._ready = threading.Event()
        self._bound_port: Optional[int] = None
        self._shutdown = threading.Event()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._pool = ThreadPoolExecutor(
            max_workers=int(os.environ.get(
                "RAY_TPU_GATEWAY_POOL", "32")),
            thread_name_prefix="gateway-generate")
        threading.Thread(target=self._serve_thread, daemon=True,
                         name="gateway-http").start()
        gateway_metrics()

    # --------------------------------------------------------- control

    def ready(self) -> tuple:
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("gateway HTTP server failed to start")
        return (self._host, self._bound_port)

    def stop(self) -> bool:
        self._shutdown.set()
        self.publish_telemetry(force=True)
        return True

    def _chaos_fire(self, _code: int) -> None:
        self._chaos_fired = True

    def _consume_chaos(self, hook: str, n: int = 1) -> bool:
        """Advance the chaos monkey's request/token counters; True
        when a drop_connection action fired on THIS call (handlers run
        on the single loop thread, so fire attribution is race-free)."""
        if self._chaos is None:
            return False
        if hook == "request":
            self._chaos.on_request()
        else:
            self._chaos.on_tokens(n)
        if self._chaos_fired:
            self._chaos_fired = False
            return True
        return False

    def reset_chaos_counts(self) -> bool:
        if self._chaos is not None:
            self._chaos.reset_counts()
        return True

    # ------------------------------------------------------- accounting

    def _count(self, route: str, cls: str, code: int) -> None:
        with self._lock:
            key = str(code)
            self._by_code[key] = self._by_code.get(key, 0) + 1
            if code == 429:
                self._stats["rate_limited"] += 1
            elif code in (499,):
                self._stats["disconnects"] += 1
                if cls in self._by_class:
                    self._by_class[cls]["disconnects"] += 1
            elif code in (503,):
                self._stats["sheds"] += 1
                if cls in self._by_class:
                    self._by_class[cls]["shed"] += 1
            elif code >= 400:
                self._stats["errors"] += 1
        gateway_metrics()["requests"].inc(
            tags={"route": route, "class": cls, "code": str(code)})
        self.publish_telemetry()

    def _count_accept(self, route: str, cls: str,
                      tenant: Optional[str]) -> None:
        with self._lock:
            self._stats["accepted"] += 1
            if cls in self._by_class:
                self._by_class[cls]["accepted"] += 1
        push_gateway_event({"kind": "accept", "gateway": self.gateway_id,
                            "route": route, "class": cls,
                            "tenant": tenant})
        self.publish_telemetry()

    def _count_done(self, cls: str, n_tokens: int,
                    streamed: bool) -> None:
        with self._lock:
            self._stats["completed"] += 1
            self._stats["tokens_out"] += int(n_tokens)
            if streamed:
                self._stats["streamed"] += 1
            if cls in self._by_class:
                self._by_class[cls]["completed"] += 1

    def _first_byte(self, cls: str, ttft_ms: float) -> None:
        self._ttft_win.setdefault(cls, SlidingWindow()).add(ttft_ms)
        gateway_metrics()["ttft_ms"].observe(ttft_ms,
                                             tags={"class": cls})
        push_gateway_event({"kind": "first_byte",
                            "gateway": self.gateway_id, "class": cls,
                            "ttft_ms": round(ttft_ms, 3)})

    def stats(self) -> Dict[str, Any]:
        """This replica's snapshot — the shape the conductor
        aggregates. ``preemptions`` reads the routers' own counter
        (the router fires preemptions, the gateway only causes them):
        one counter, surfaced everywhere."""
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            s["by_class"] = {c: dict(v)
                            for c, v in self._by_class.items()}
            s["by_code"] = dict(self._by_code)
        preempt = 0
        for r in self._models.values():
            try:
                preempt += int(r.stats().get("preemptions", 0))
            except Exception:  # noqa: BLE001 — router mid-teardown
                pass
        s["preemptions"] = preempt
        s["role"] = "gateway"
        s["gateway_id"] = self.gateway_id
        s["host"] = self._host
        s["port"] = self._bound_port
        s["models"] = sorted(self._models)
        s["ttft_ms"] = {c: w.summary()
                        for c, w in self._ttft_win.items()}
        if self._qos is not None:
            s["qos"] = self._qos.stats()
        return s

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        push_gateway_stats(self.gateway_id, self.stats())

    # ------------------------------------------------------ http plumbing

    def _error_body(self, message: str, err_type: str,
                    code: Optional[str]) -> Dict[str, Any]:
        return {"error": {"message": message, "type": err_type,
                          "param": None, "code": code}}

    def _client_gone(self, request) -> bool:
        t = request.transport
        return t is None or t.is_closing()

    @staticmethod
    def _shed_status(e: RequestShedError) -> int:
        return 429 if getattr(e, "cause", None) in ("rate_limit",
                                                    "quota") else 503

    @staticmethod
    def _shed_headers(e: RequestShedError) -> Dict[str, str]:
        return {"Retry-After":
                str(max(1, int(getattr(e, "retry_after_s", 1.0)))),
                "X-Shed-Cause": str(getattr(e, "cause", "capacity"))}

    def _encode_prompt(self, body: Dict[str, Any],
                       route: str) -> List[int]:
        """OpenAI request -> token ids. Raises ValueError (-> 400)."""
        if route == "chat":
            messages = body.get("messages")
            if not isinstance(messages, list) or not messages:
                raise ValueError("'messages' must be a non-empty list")
            parts = []
            for m in messages:
                if not isinstance(m, dict) or "content" not in m:
                    raise ValueError(
                        "each message needs 'role' and 'content'")
                parts.append(f"{m.get('role', 'user')}: {m['content']}")
            return self._codec.encode("\n".join(parts))
        prompt = body.get("prompt")
        if isinstance(prompt, str):
            return self._codec.encode(prompt)
        if isinstance(prompt, list) and prompt and \
                all(isinstance(t, int) for t in prompt):
            return [int(t) for t in prompt]
        raise ValueError(
            "'prompt' must be a string or a list of token ids")

    def _completion_payload(self, route: str, req_id: str,
                            created: int, model: str, text: str,
                            finish: Optional[str],
                            n_prompt: int, n_out: int,
                            chunk: bool = False,
                            first_chunk: bool = False
                            ) -> Dict[str, Any]:
        if route == "chat":
            if chunk:
                delta: Dict[str, Any] = {"content": text}
                if first_chunk:
                    delta["role"] = "assistant"
                choice: Dict[str, Any] = {"index": 0, "delta": delta,
                                          "finish_reason": finish}
                obj = "chat.completion.chunk"
            else:
                choice = {"index": 0,
                          "message": {"role": "assistant",
                                      "content": text},
                          "finish_reason": finish}
                obj = "chat.completion"
        else:
            choice = {"index": 0, "text": text,
                      "finish_reason": finish}
            obj = "text_completion"
        out = {"id": req_id, "object": obj, "created": created,
               "model": model, "choices": [choice]}
        if not chunk:
            out["usage"] = {"prompt_tokens": n_prompt,
                            "completion_tokens": n_out,
                            "total_tokens": n_prompt + n_out}
        return out

    # ------------------------------------------------------ the handlers

    async def _handle(self, request, route: str):
        """Parse/authenticate/admit, then dispatch to the streaming or
        blocking bridge. Every early exit counts into
        requests_total{route,class,code} — the class is "-" until the
        request names one."""
        from aiohttp import web

        cls = "-"
        tenant: Optional[str] = None
        admitted = False
        # the request id is minted BEFORE parsing so even a 400 carries
        # a correlatable X-Request-Id (the middleware stamps whatever
        # this handler left in request["req_id"]); an incoming W3C
        # traceparent bridges the caller's trace id into the flight
        # recorder
        t_req = time.perf_counter()
        req_id = (f"cmpl-{uuid.uuid4().hex[:24]}" if route != "chat"
                  else f"chatcmpl-{uuid.uuid4().hex[:24]}")
        request["req_id"] = req_id
        tp_in = request.headers.get("traceparent")
        try:
            try:
                body = json.loads((await request.read()) or b"")
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except (ValueError, UnicodeDecodeError):
                self._count(route, cls, 400)
                return web.json_response(self._error_body(
                    "request body is not a valid JSON object",
                    "invalid_request_error", "invalid_json"),
                    status=400)
            model = body.get("model")
            if model is None and len(self._models) == 1:
                model = next(iter(self._models))
            router = self._models.get(model)
            if router is None:
                self._count(route, cls, 404)
                return web.json_response(self._error_body(
                    f"model {model!r} does not exist",
                    "invalid_request_error", "model_not_found"),
                    status=404)
            auth = request.headers.get("Authorization", "")
            api_key = auth[7:] if auth.startswith("Bearer ") else None
            hint = request.headers.get("X-Tenant") or body.get("user")
            try:
                tenant = (self._qos.resolve(api_key, hint)
                          if self._qos is not None else hint)
            except PermissionError:
                self._count(route, cls, 401)
                return web.json_response(self._error_body(
                    "invalid API key", "authentication_error",
                    "invalid_api_key"), status=401)
            requested_cls = (body.get("priority")
                             or request.headers.get("X-Priority"))
            try:
                if self._qos is not None:
                    cls = self._qos.classify(tenant, requested_cls)
                else:
                    cls = requested_cls or INTERACTIVE
                    if cls not in CLASSES:
                        raise ValueError(
                            f"unknown priority class {cls!r}")
                prompt_tokens = self._encode_prompt(body, route)
                max_tokens = int(body.get(
                    "max_tokens", self.default_max_tokens))
                max_tokens = max(1, min(max_tokens,
                                        self.max_tokens_cap))
                deadline_s = self.default_deadline_s
                hdr = request.headers.get("X-Request-Deadline")
                if hdr:
                    deadline_s = float(hdr)
                # bench/test extension: router-side slow-client pacing
                # (bench_serve's backpressure knob) — tiny research
                # checkpoints decode faster than any real socket, so
                # real-pacing scenarios need the stream held open
                token_sleep_s = min(
                    1.0, max(0.0, float(body.get("token_sleep_s", 0))))
            except (TypeError, ValueError) as e:
                self._count(route, cls, 400)
                return web.json_response(self._error_body(
                    str(e), "invalid_request_error", None),
                    status=400)
            if self._qos is not None:
                try:
                    self._qos.admit(tenant, cls)
                    admitted = True
                except RequestShedError as e:
                    status = self._shed_status(e)
                    self._count(route, cls, status)
                    # a gate-shed request still leaves a trace — shed
                    # outcomes are always retained, so the tail report
                    # sees admission rejections, not just completions
                    tr = reqtrace.start_trace(
                        req_id, source="gateway", traceparent=tp_in,
                        tenant=tenant, cls=cls, t0=t_req)
                    if tr is not None:
                        tr.add_phase(
                            "qos_admission",
                            (time.perf_counter() - t_req) * 1e3)
                        tr.finish("shed",
                                  cause=getattr(e, "cause", None))
                    return web.json_response(
                        self._error_body(str(e), "rate_limit_error",
                                         getattr(e, "cause", None)),
                        status=status, headers=self._shed_headers(e))
            self._count_accept(route, cls, tenant)
            if self._consume_chaos("request"):
                # scripted drop at admission: the socket dies before
                # any byte of response — the client sees a reset
                if request.transport is not None:
                    request.transport.abort()
                self._count(route, cls, 499)
                raise ConnectionResetError("chaos drop_connection")
            created = int(time.time())
            # the flight-recorder trace: t0 backdated to handler entry
            # so qos_admission covers parse + auth + classify + admit
            tr = reqtrace.start_trace(
                req_id, source="gateway", traceparent=tp_in,
                tenant=tenant, cls=cls, t0=t_req)
            if tr is not None:
                tr.add_phase("qos_admission",
                             (time.perf_counter() - t_req) * 1e3)
            ctx = dict(route=route, cls=cls, tenant=tenant,
                       router=router, model=model or "",
                       prompt_tokens=prompt_tokens,
                       max_tokens=max_tokens, deadline_s=deadline_s,
                       token_sleep_s=token_sleep_s,
                       req_id=req_id, created=created, trace=tr)
            if body.get("stream"):
                return await self._stream_response(request, ctx)
            return await self._block_response(request, ctx)
        finally:
            if admitted:
                self._qos.release(tenant)

    def _generate_kwargs(self, ctx: Dict[str, Any]) -> Dict[str, Any]:
        kw = dict(eos_token=self._eos_token,
                  timeout_s=self.request_timeout_s,
                  deadline_s=ctx["deadline_s"],
                  token_sleep_s=ctx.get("token_sleep_s") or 0.0,
                  priority=ctx["cls"])
        # the tenant reaches the DATA plane only on a LoRA-enabled
        # deployment (adapter routing, namespace-keyed KV, per-tenant
        # router accounting); an explicit tenant on a pool-less tier
        # fails loudly by design, so a plain deployment keeps the
        # tenant at the QoS layer
        router = ctx["router"]
        try:
            lora = bool(router._lora_enabled())
        except Exception:  # noqa: BLE001 — non-DisaggRouter backend
            lora = False
        if lora:
            kw["tenant"] = ctx["tenant"]
        return kw

    async def _block_response(self, request, ctx: Dict[str, Any]):
        """Non-streaming bridge: the blocking generate runs on the
        executor pool (never on the loop); a client that disconnects
        while waiting cancels the decode through the same reap path
        as a mid-stream drop."""
        from aiohttp import web

        loop = asyncio.get_running_loop()
        route, cls = ctx["route"], ctx["cls"]
        router = ctx["router"]
        tr = ctx.get("trace")
        cancel_event = threading.Event()
        t0 = time.perf_counter()
        kwargs = self._generate_kwargs(ctx)
        kwargs["cancel_event"] = cancel_event

        def work():
            # activate on the EXECUTOR thread: the router's generate —
            # and every in-process tier hop under it — stamps phases
            # onto this request's trace through the thread-local
            with reqtrace.activate(tr):
                return router.generate(ctx["prompt_tokens"],
                                       ctx["max_tokens"], **kwargs)

        try:
            toks = await loop.run_in_executor(self._pool, work)
        except asyncio.CancelledError:
            # aiohttp cancelled the handler: the client went away
            cancel_event.set()
            self._count(route, cls, 499)
            push_gateway_event({"kind": "disconnect",
                                "gateway": self.gateway_id,
                                "class": cls, "phase": "waiting"})
            if tr is not None:
                tr.finish("disconnect", cause="client_gone")
            raise
        except RequestShedError as e:
            status = self._shed_status(e)
            self._count(route, cls, status)
            if tr is not None:
                outcome, cause = shed_outcome(e)
                tr.finish(outcome, cause=cause)
            return web.json_response(
                self._error_body(str(e), "rate_limit_error"
                                 if status == 429 else "overloaded",
                                 getattr(e, "cause", None)),
                status=status, headers=self._shed_headers(e))
        except ValueError as e:
            self._count(route, cls, 400)
            if tr is not None:
                tr.finish("error", cause=type(e).__name__)
            return web.json_response(self._error_body(
                str(e), "invalid_request_error", None), status=400)
        except Exception as e:  # noqa: BLE001 — surface as 500
            self._count(route, cls, 500)
            if tr is not None:
                tr.finish("error", cause=type(e).__name__)
            return web.json_response(self._error_body(
                f"{type(e).__name__}: {e}", "api_error", None),
                status=500)
        self._first_byte(cls, (time.perf_counter() - t0) * 1e3)
        text = self._codec.decode(toks)
        finish = ("stop" if self._eos_token is not None and toks
                  and toks[-1] == int(self._eos_token) else "length")
        self._count_done(cls, len(toks), streamed=False)
        self._count(route, cls, 200)
        if tr is not None:
            tr.finish("ok", tokens=len(toks))
        return web.json_response(self._completion_payload(
            route, ctx["req_id"], ctx["created"], ctx["model"], text,
            finish, len(ctx["prompt_tokens"]), len(toks)))

    async def _stream_response(self, request, ctx: Dict[str, Any]):
        """SSE bridge: generate runs on the executor; its on_tokens
        chunks land on an asyncio queue (call_soon_threadsafe) and are
        re-framed as OpenAI stream chunks. Each delta is the decode of
        all tokens so far minus what was already sent, so concatenated
        deltas are EXACTLY the non-streaming body. Disconnects —
        noticed by a failed write, by aiohttp cancelling the handler,
        or by transport polling while decode is quiet — set the cancel
        event; the router sheds the request with cause ``disconnect``
        and the decode slot frees instead of finishing the stream
        nobody reads."""
        from aiohttp import web

        loop = asyncio.get_running_loop()
        route, cls = ctx["route"], ctx["cls"]
        router = ctx["router"]
        tr = ctx.get("trace")
        cancel_event = threading.Event()
        q: asyncio.Queue = asyncio.Queue()
        t0 = time.perf_counter()
        # sse_flush accounting: wall time spent inside resp.write —
        # concurrent with decode (the executor keeps generating while
        # the loop flushes), so the phase is marked concurrent and
        # excluded from the phase-sum invariant
        flush_s = 0.0
        flush_n = 0

        def _put(item):
            try:
                loop.call_soon_threadsafe(q.put_nowait, item)
            except RuntimeError:  # loop shut down mid-request
                cancel_event.set()

        kwargs = self._generate_kwargs(ctx)
        kwargs["cancel_event"] = cancel_event
        kwargs["on_tokens"] = lambda toks: _put(("tokens", list(toks)))

        def work():
            try:
                with reqtrace.activate(tr):
                    out = router.generate(ctx["prompt_tokens"],
                                          ctx["max_tokens"], **kwargs)
                _put(("done", out))
            except BaseException as e:  # noqa: BLE001 — relayed
                _put(("error", e))

        def _finish(outcome, cause=None, **attrs):
            if tr is None:
                return
            if flush_s > 0.0:
                tr.add_phase("sse_flush", flush_s * 1e3,
                             concurrent=True, writes=flush_n)
            tr.finish(outcome, cause=cause, **attrs)

        # the status line is written lazily at the FIRST frame: a
        # request the router sheds before producing anything (capacity,
        # quota, deadline) still gets a real 429/503 status response —
        # only a shed that lands mid-stream has to ride an SSE error
        # frame, because by then the 200 is already on the wire
        resp = web.StreamResponse(status=200)
        resp.headers["Content-Type"] = "text/event-stream"
        resp.headers["Cache-Control"] = "no-cache"
        # set pre-prepare: once the SSE status line is on the wire the
        # middleware can no longer add headers
        resp.headers["X-Request-Id"] = ctx["req_id"]
        resp.enable_chunked_encoding()
        prepared = False

        async def _prepare_once():
            nonlocal prepared
            if not prepared:
                await resp.prepare(request)
                prepared = True

        self._pool.submit(work)
        got: List[int] = []
        sent_text = ""
        first = True
        disconnected = False
        failed: Optional[BaseException] = None
        try:
            while True:
                # poll the transport every pass, not only when the
                # queue is quiet — under a steady token stream the
                # queue never drains and a dead socket would
                # otherwise go unnoticed until a write bounced
                if self._client_gone(request):
                    disconnected = True
                    break
                try:
                    kind, payload = await asyncio.wait_for(
                        q.get(), timeout=0.5)
                except asyncio.TimeoutError:
                    continue
                if kind == "tokens":
                    got.extend(payload)
                    text = self._codec.decode(got)
                    delta, sent_text = text[len(sent_text):], text
                    try:
                        t_w = time.perf_counter()
                        await _prepare_once()
                        await resp.write(_sse_frame(
                            self._completion_payload(
                                route, ctx["req_id"], ctx["created"],
                                ctx["model"], delta, None, 0, 0,
                                chunk=True, first_chunk=first)))
                        flush_s += time.perf_counter() - t_w
                        flush_n += 1
                    except _CLIENT_GONE:
                        disconnected = True
                        break
                    if first:
                        first = False
                        self._first_byte(
                            cls, (time.perf_counter() - t0) * 1e3)
                    if self._consume_chaos("tokens", len(payload)):
                        if request.transport is not None:
                            request.transport.abort()
                        disconnected = True
                        break
                elif kind == "done":
                    toks = payload
                    finish = ("stop" if self._eos_token is not None
                              and toks
                              and toks[-1] == int(self._eos_token)
                              else "length")
                    try:
                        t_w = time.perf_counter()
                        await _prepare_once()
                        await resp.write(_sse_frame(
                            self._completion_payload(
                                route, ctx["req_id"], ctx["created"],
                                ctx["model"], "", finish, 0, 0,
                                chunk=True)))
                        await resp.write(_sse_frame(b"[DONE]"))
                        await resp.write_eof()
                        flush_s += time.perf_counter() - t_w
                        flush_n += 1
                    except _CLIENT_GONE:
                        disconnected = True
                        break
                    self._count_done(cls, len(toks), streamed=True)
                    self._count(route, cls, 200)
                    _finish("ok", tokens=len(toks), streamed=True)
                    return resp
                else:  # error relayed from the executor
                    failed = payload
                    break
        except asyncio.CancelledError:
            cancel_event.set()
            self._count(route, cls, 499)
            push_gateway_event({"kind": "disconnect",
                                "gateway": self.gateway_id,
                                "class": cls, "phase": "streaming"})
            _finish("disconnect", cause="client_gone",
                    tokens_sent=len(got))
            raise
        if disconnected:
            cancel_event.set()
            self._count(route, cls, 499)
            push_gateway_event({"kind": "disconnect",
                                "gateway": self.gateway_id,
                                "class": cls, "phase": "streaming",
                                "tokens_sent": len(got)})
            _finish("disconnect", cause="client_gone",
                    tokens_sent=len(got))
            return resp
        if isinstance(failed, RequestShedError):
            status = self._shed_status(failed)
            err_type = ("rate_limit_error" if status == 429
                        else "overloaded")
            headers = self._shed_headers(failed)
            outcome, cause = shed_outcome(failed)
            _finish(outcome, cause=cause)
        elif isinstance(failed, ValueError):
            status, err_type, headers = 400, "invalid_request_error", {}
            _finish("error", cause=type(failed).__name__)
        else:
            status, err_type, headers = 500, "api_error", {}
            _finish("error", cause=type(failed).__name__
                    if failed is not None else None)
        self._count(route, cls, status)
        body = self._error_body(str(failed), err_type,
                                getattr(failed, "cause", None))
        if not prepared:
            # nothing on the wire yet: the shed gets a real status
            # line, same shape as the non-streaming path
            return web.json_response(body, status=status,
                                     headers=headers)
        # mid-stream failure: headers are long gone — terminate the
        # event stream with an error frame + [DONE] so a compliant
        # client stops reading instead of hanging
        try:
            await resp.write(_sse_frame(body))
            await resp.write(_sse_frame(b"[DONE]"))
            await resp.write_eof()
        except _CLIENT_GONE:
            pass
        return resp

    # ---------------------------------------------------- server thread

    def _serve_thread(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def completions(request):
            return await self._handle(request, "completions")

        async def chat(request):
            return await self._handle(request, "chat")

        async def models(_request):
            return web.json_response({
                "object": "list",
                "data": [{"id": m, "object": "model",
                          "owned_by": "ray_tpu"}
                         for m in sorted(self._models)]})

        async def healthz(_request):
            return web.Response(text="ok")

        async def snapshot(_request):
            return web.json_response(json.loads(
                json.dumps(self.stats(), default=str)))

        @web.middleware
        async def request_id_mw(request, handler):
            # every response — 2xx, 4xx/5xx error bodies, /v1/models,
            # healthz — carries X-Request-Id. The completion handlers
            # mint a route-prefixed id into request["req_id"]; anything
            # else (or an early rejection before the mint) gets a
            # req- fallback so clients can always quote an id back.
            try:
                resp = await handler(request)
            except web.HTTPException as e:
                rid = request.get("req_id") or \
                    f"req-{uuid.uuid4().hex[:24]}"
                e.headers.setdefault("X-Request-Id", rid)
                raise
            rid = request.get("req_id") or \
                f"req-{uuid.uuid4().hex[:24]}"
            # SSE StreamResponses set the header pre-prepare in the
            # handler; a prepared response's headers are on the wire
            if not getattr(resp, "prepared", False):
                resp.headers.setdefault("X-Request-Id", rid)
            return resp

        app = web.Application(client_max_size=64 * 1024 * 1024,
                              middlewares=[request_id_mw])
        app.router.add_post("/v1/completions", completions)
        app.router.add_post("/v1/chat/completions", chat)
        app.router.add_get("/v1/models", models)
        app.router.add_get("/-/healthz", healthz)
        app.router.add_get("/-/gateway", snapshot)

        async def run():
            runner = web.AppRunner(app)
            await runner.setup()
            port = self._port
            site = None
            for _attempt in range(20):  # skip ports already in use
                try:
                    site = web.TCPSite(runner, self._host, port)
                    await site.start()
                    break
                except OSError:
                    if port == 0:  # ephemeral bind cannot EADDRINUSE
                        raise
                    port += 1
                    site = None
            if site is None:
                raise RuntimeError("could not bind gateway port")
            if port == 0:
                port = site._server.sockets[0].getsockname()[1]
            self._bound_port = port
            self._ready.set()
            self.publish_telemetry(force=True)
            while not self._shutdown.is_set():
                await asyncio.sleep(0.2)
            await runner.cleanup()

        loop.run_until_complete(run())


__all__ = ["ByteCodec", "GatewayServer"]
