"""Declarative Serve application schema + YAML deploy path.

Analog of the reference's python/ray/serve/schema.py (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema, pydantic there — plain
dataclasses with strict key validation here) and the config-file half of
serve/scripts.py `serve run|deploy` (:147-746).

A config file looks like:

    http_options:
      host: 127.0.0.1
      port: 8000
    applications:
      - name: default
        route_prefix: /
        import_path: my_module:app        # module:attr -> Application
        deployments:                       # optional per-name overrides
          - name: Model
            num_replicas: 3
            max_ongoing_requests: 16
            autoscaling_config:
              min_replicas: 1
              max_replicas: 8

`deploy_config(schema)` imports each application, applies the overrides,
deploys through the controller, and records the config in the cluster KV
so `serve config` can echo it back from any process.
"""
from __future__ import annotations

import dataclasses
import importlib
import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .config import AutoscalingConfig, HTTPOptions

_KV_CONFIG_KEY = b"serve:deploy_config"


def _check_keys(data: Dict[str, Any], cls, where: str) -> None:
    allowed = {f.name for f in dataclasses.fields(cls)}
    unknown = set(data) - allowed
    if unknown:
        raise ValueError(
            f"unknown field(s) {sorted(unknown)} in {where}; "
            f"allowed: {sorted(allowed)}")


@dataclass
class DeploymentSchema:
    """Per-deployment override block — reference schema.py
    DeploymentSchema. Unset fields (None) leave the code-side value."""
    name: str = ""
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    user_config: Optional[Any] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    health_check_period_s: Optional[float] = None
    health_check_timeout_s: Optional[float] = None
    graceful_shutdown_timeout_s: Optional[float] = None
    ray_actor_options: Optional[Dict[str, Any]] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "DeploymentSchema":
        _check_keys(data, cls, f"deployment {data.get('name', '?')!r}")
        if not data.get("name"):
            raise ValueError("every deployment override needs a 'name'")
        return cls(**data)

    def to_options(self) -> Dict[str, Any]:
        opts = {f.name: getattr(self, f.name)
                for f in dataclasses.fields(self)
                if f.name != "name" and getattr(self, f.name) is not None}
        if "autoscaling_config" in opts:
            _check_keys(opts["autoscaling_config"], AutoscalingConfig,
                        f"autoscaling_config of {self.name!r}")
        return opts


@dataclass
class ServeApplicationSchema:
    """One application — reference schema.py ServeApplicationSchema."""
    import_path: str = ""
    name: str = "default"
    route_prefix: str = "/"
    args: Dict[str, Any] = field(default_factory=dict)
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ServeApplicationSchema":
        _check_keys(data, cls, f"application {data.get('name', '?')!r}")
        if not data.get("import_path"):
            raise ValueError(
                f"application {data.get('name', '?')!r} needs an "
                "'import_path' of the form 'module:attribute'")
        deployments = [DeploymentSchema.from_dict(d)
                       for d in data.get("deployments", [])]
        return cls(import_path=data["import_path"],
                   name=data.get("name", "default"),
                   route_prefix=data.get("route_prefix", "/"),
                   args=dict(data.get("args", {})),
                   deployments=deployments)

    def to_dict(self) -> Dict[str, Any]:
        return {"import_path": self.import_path, "name": self.name,
                "route_prefix": self.route_prefix, "args": self.args,
                "deployments": [dataclasses.asdict(d)
                                for d in self.deployments]}


@dataclass
class ServeDeploySchema:
    """Top-level config — reference schema.py ServeDeploySchema."""
    applications: List[ServeApplicationSchema] = field(default_factory=list)
    http_options: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "ServeDeploySchema":
        _check_keys(data, cls, "serve config")
        apps = [ServeApplicationSchema.from_dict(a)
                for a in data.get("applications", [])]
        if not apps:
            raise ValueError("serve config declares no applications")
        names = [a.name for a in apps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate application names: {names}")
        http = dict(data.get("http_options", {}))
        _check_keys(http, HTTPOptions, "http_options")
        return cls(applications=apps, http_options=http)

    @classmethod
    def from_yaml_file(cls, path: str) -> "ServeDeploySchema":
        import yaml

        with open(path) as f:
            data = yaml.safe_load(f) or {}
        if not isinstance(data, dict):
            raise ValueError(f"{path} is not a mapping")
        return cls.from_dict(data)

    def to_dict(self) -> Dict[str, Any]:
        return {"applications": [a.to_dict() for a in self.applications],
                "http_options": self.http_options}


def import_attr(import_path: str):
    """'pkg.module:attr' -> the attribute (reference
    ray._private.utils.import_attr)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path {import_path!r} must be 'module:attribute'")
    module_name, _, attr = import_path.partition(":")
    module = importlib.import_module(module_name)
    obj = module
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def _override_deployments(target, overrides: List[DeploymentSchema]):
    """Apply per-name option overrides to every Deployment reachable from
    the bound application graph. Returns the names actually overridden so
    a typo'd name fails loudly instead of silently deploying defaults."""
    from . import Application, Deployment

    by_name = {o.name: o for o in overrides}
    hit = set()

    def visit(obj):
        if isinstance(obj, Application):
            dep = obj._deployment
            o = by_name.get(dep.name)
            if o is not None and dep.name not in hit:
                hit.add(dep.name)
                newdep = dep.options(**o.to_options())
                dep.config = newdep.config
            for a in obj._args:
                visit(a)
            for v in obj._kwargs.values():
                visit(v)
        elif isinstance(obj, (list, tuple)):
            for x in obj:
                visit(x)
        elif isinstance(obj, dict):
            for v in obj.values():
                visit(v)

    if isinstance(target, Deployment):
        target = target.bind()
    visit(target)
    missing = set(by_name) - hit
    if missing:
        raise ValueError(
            f"deployment override(s) {sorted(missing)} match no deployment "
            "in the application graph")
    return target


def deploy_config(schema: ServeDeploySchema) -> List[str]:
    """Build and deploy every application in the schema; returns the
    deployed app names. Idempotent: re-deploying an existing app replaces
    it (the controller drains the old replicas) — the declarative
    update path of reference `serve deploy`."""
    from . import run, start
    from .config import HTTPOptions as HTTP

    start(HTTP(**schema.http_options) if schema.http_options else None)
    deployed = []
    for app in schema.applications:
        from . import Application, Deployment

        target = import_attr(app.import_path)
        if isinstance(target, Deployment):
            target = target.bind(**app.args) if app.args else target.bind()
        elif isinstance(target, Application):
            if app.args:
                raise ValueError(
                    f"application {app.name!r}: 'args' requires the "
                    "import_path to point at a Deployment or a builder "
                    "function, not an already-bound Application")
        elif callable(target):
            # app builder: def build(args: dict) -> Application
            # (reference: serve.run's builder-function import path)
            target = target(app.args)
        else:
            raise TypeError(
                f"{app.import_path} resolved to {type(target).__name__}; "
                "expected Deployment, Application, or builder function")
        target = _override_deployments(target, app.deployments)
        run(target, name=app.name, route_prefix=app.route_prefix)
        deployed.append(app.name)
    _record_config(schema)
    return deployed


def _record_config(schema: ServeDeploySchema) -> None:
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return
    try:
        w.conductor.call(
            "kv_put", _KV_CONFIG_KEY,
            json.dumps(schema.to_dict()).encode(), True, "serve",
            timeout=10.0)
    except Exception:  # noqa: BLE001 — config echo is best-effort
        pass


def get_deployed_config() -> Optional[Dict[str, Any]]:
    """The last schema deployed through deploy_config, from cluster KV —
    reference `serve config` (scripts.py:543)."""
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker
    if w is None:
        return None
    raw = w.conductor.call("kv_get", _KV_CONFIG_KEY, "serve", timeout=10.0)
    return json.loads(raw.decode()) if raw else None
