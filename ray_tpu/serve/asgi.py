"""ASGI ingress adapter — `@serve.ingress(asgi_app)`.

Reference: python/ray/serve/api.py `ingress` + _private/http_util.py
ASGIAppReplicaWrapper: a deployment class decorated with an ASGI
application (FastAPI, Starlette, or any bare `async def app(scope,
receive, send)`) serves every HTTP request routed to it through that
app. The reference embeds uvicorn's protocol machinery; here the proxy
already parsed the request, so the adapter just speaks the ASGI
`http.request` / `http.response.*` message protocol directly — no
server dependency, works with any spec-compliant app.
"""
from __future__ import annotations

from typing import Any, Callable

from .http_util import Request, Response


def _build_scope(request: Request, root_path: str = "") -> dict:
    path = request.path
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": path,
        "raw_path": path.encode(),
        # the deployment's route prefix: frameworks route on
        # path[len(root_path):], so @app.get("/hello") matches under
        # route_prefix="/api" (reference sets root_path the same way)
        "root_path": root_path,
        "query_string": request.query_string.encode(),
        "headers": [(k.lower().encode(), v.encode())
                    for k, v in request.headers.items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
    }


async def _run_asgi(app: Callable, request: Request,
                    root_path: str = "") -> Response:
    body_sent = False

    async def receive():
        nonlocal body_sent
        if body_sent:
            # the request body was fully delivered; a second receive()
            # means the app is waiting for the connection to close
            return {"type": "http.disconnect"}
        body_sent = True
        return {"type": "http.request", "body": request.body,
                "more_body": False}

    out = {"status": 200, "headers": [], "body": bytearray()}

    async def send(message):
        if message["type"] == "http.response.start":
            out["status"] = message["status"]
            out["headers"] = list(message.get("headers", []))
        elif message["type"] == "http.response.body":
            out["body"] += message.get("body", b"")

    await app(_build_scope(request, root_path), receive, send)
    headers = [(k.decode("latin-1"), v.decode("latin-1"))
               for k, v in out["headers"]]  # pairs: duplicates survive
    return Response(bytes(out["body"]), status=out["status"],
                    headers=headers)


def ingress(asgi_app: Any) -> Callable[[type], type]:
    """Class decorator: route this deployment's HTTP traffic through
    `asgi_app` — any ASGI-3 callable, including a bare
    ``async def app(scope, receive, send)``, a Starlette app, or a
    FastAPI app whose routes are module-level functions:

        app = FastAPI()

        @app.get("/hello")
        def hello():
            return "hi"

        @serve.deployment
        @serve.ingress(app)
        class Api:
            pass

    Unlike the reference's make_fastapi_class_based_view, routes defined
    as METHODS of the deployment class (taking ``self``) are NOT bound —
    keep FastAPI/Starlette routes self-less, with per-replica state on
    the class reachable via closure or app.state if needed.
    """
    def decorator(cls: type) -> type:
        if not isinstance(cls, type):
            raise TypeError("@serve.ingress decorates a class (apply it "
                            "under @serve.deployment)")

        class ASGIIngressWrapper(cls):  # type: ignore[misc, valid-type]
            async def __call__(self, request: Request) -> Response:
                from .context import get_request_context

                prefix = get_request_context().route
                root = "" if prefix in ("", "/") \
                    else prefix.rstrip("/")
                return await _run_asgi(asgi_app, request, root)

        ASGIIngressWrapper.__name__ = cls.__name__
        ASGIIngressWrapper.__qualname__ = getattr(cls, "__qualname__",
                                                  cls.__name__)
        ASGIIngressWrapper.__module__ = cls.__module__
        ASGIIngressWrapper.__asgi_app__ = asgi_app
        return ASGIIngressWrapper

    return decorator


__all__ = ["ingress", "Response"]
