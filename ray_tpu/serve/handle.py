"""DeploymentHandle + router — analog of the reference's
python/ray/serve/handle.py (DeploymentHandle :711, DeploymentResponse) and
_private/router.py:297 + replica_scheduler/pow_2_scheduler.py:49.

Replica choice is power-of-two-choices over cached queue lengths: the router
keeps a per-replica in-flight estimate (incremented on submit, decremented on
completion) and periodically reconciles against replica-reported queue
lengths, like the reference's cached RunningReplica queue-length probes."""
from __future__ import annotations

import logging
import os
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"


class RequestShedError(RuntimeError):
    """Raised by admission control instead of queueing past the knob
    (router load shedding — reject-with-retry-after, shed BEFORE the
    replica/engine wedges). ``retry_after_s`` is the client's backoff
    hint; the HTTP proxy maps it to a 503 + Retry-After header.

    ``cause`` attributes the shed (the serving-fault-tolerance
    invariant: an accepted request is never silently dropped — it
    either completes or sheds WITH a cause): ``capacity`` (admission
    bound), ``deadline`` (request outlived its deadline_s),
    ``failover`` (replica deaths exhausted the bounded retry budget),
    ``draining`` (dispatch raced a replica's grace drain)."""

    def __init__(self, message: str, retry_after_s: float = 1.0,
                 cause: str = "capacity"):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
        self.cause = str(cause)


_shed_counter = None
_shed_counter_lock = threading.Lock()


def shed_counter():
    """Process-wide shed counter (lazy — importing serve must not spawn
    a metrics pusher), shared by the Router and the disagg router so
    `ray_tpu_serve_shed_total` covers every shed path."""
    global _shed_counter
    c = _shed_counter
    if c is not None:
        return c
    with _shed_counter_lock:
        if _shed_counter is None:
            from ray_tpu.util.metrics import Counter

            _shed_counter = Counter(
                "ray_tpu_serve_shed_total",
                "requests rejected by admission control (queue depth "
                "past the knob)", tag_keys=("app", "deployment"))
    return _shed_counter


class RequestMetadata:
    def __init__(self, call_method: str = "__call__",
                 multiplexed_model_id: str = "", is_http: bool = False,
                 app_name: str = "", route: str = ""):
        self.call_method = call_method
        self.multiplexed_model_id = multiplexed_model_id
        self.is_http = is_http
        self.app_name = app_name
        self.route = route

    def to_dict(self) -> Dict[str, Any]:
        return dict(call_method=self.call_method,
                    multiplexed_model_id=self.multiplexed_model_id,
                    is_http=self.is_http, app_name=self.app_name,
                    route=self.route)


class DeploymentResponse:
    """Future-like result of handle.remote() — reference handle.py
    DeploymentResponse. Pass it to another handle call and it resolves to the
    underlying ObjectRef (model composition without driver round-trips)."""

    def __init__(self, object_ref, router: "Router", replica_tag: str,
                 request: Optional[tuple] = None):
        self._object_ref = object_ref
        self._router = router
        self._replica_tag = replica_tag
        self._done = False
        # (meta, args, kwargs) for the dead-replica retry in result()
        self._request = request

    def result(self, timeout_s: Optional[float] = None) -> Any:
        import ray_tpu
        from ray_tpu.exceptions import ActorDiedError, TaskError

        try:
            return ray_tpu.get(self._object_ref, timeout=timeout_s)
        except ActorDiedError:
            # The replica died with this request in flight — the exact
            # redeploy/drain window. A request that died with its replica
            # never completed, so re-assigning it to a live replica is
            # safe (reference router behavior: dead-replica requests are
            # retried against the refreshed replica set).
            self._mark_done()
            if self._request is None:
                raise
            meta, args, kwargs = self._request
            self._router._refresh(force=True)
            retried = self._router.assign(meta, args, kwargs)
            retried._request = None  # one retry: a second death raises
            return retried.result(timeout_s)
        except TaskError as e:
            # A replica that began its grace drain rejects the request
            # before running it (replica.py _reject_if_draining) — the
            # same raced-teardown window as a death, so retry the same
            # way; exhausted retries surface the ATTRIBUTED shed, not
            # the opaque TaskError wrapper.
            shed = e.cause if isinstance(e.cause, RequestShedError) \
                else None
            if shed is None or shed.cause != "draining":
                raise
            self._mark_done()
            if self._request is None:
                raise shed from e
            meta, args, kwargs = self._request
            self._router._refresh(force=True)
            retried = self._router.assign(meta, args, kwargs)
            retried._request = None
            return retried.result(timeout_s)
        finally:
            self._mark_done()

    async def result_async(self, timeout_s: Optional[float] = None) -> Any:
        """Loop-safe result(): the blocking get — and the dead-replica
        retry inside it, whose re-pick may wait for a replacement
        replica — runs on the default executor, so an async deployment
        method can `await resp.result_async()` (or just `await resp`)
        without stalling its event loop."""
        import asyncio
        import functools

        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self.result, timeout_s))

    def __await__(self):
        # `resp = await handle.remote_async(x); y = await resp`
        return self.result_async().__await__()

    def _to_object_ref(self):
        self._mark_done()
        return self._object_ref

    def _mark_done(self):
        if not self._done:
            self._done = True
            self._router._complete(self._replica_tag)

    def __del__(self):
        # Fire-and-forget callers drop the response without result();
        # release the router's in-flight slot so pow-2 routing and the
        # autoscaler metrics don't leak upward forever.
        try:
            self._mark_done()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class StreamingDeploymentResponse:
    """Iterator over chunks a replica streams back (reference
    handle.py DeploymentResponseGenerator / replica handle_request_
    streaming). Chunks arrive as stream_chunk pushes into a local worker
    stream endpoint; iteration ends when the replica's final reply lands
    and every pushed chunk is consumed. If the user method returned a
    plain value instead of a generator, iteration yields nothing and
    `.value` holds the result (`.kind` tells which case occurred).

    Not picklable — consume it in the process that made the call."""

    _POLL_S = 0.05

    def __init__(self, object_ref, router: "Router", replica_tag: str,
                 stream_id: str, chunk_queue,
                 chunk_timeout_s: float = 120.0):
        self._ref = object_ref
        self._router = router
        self._replica_tag = replica_tag
        self._stream_id = stream_id
        self._queue = chunk_queue
        self._chunk_timeout_s = chunk_timeout_s
        self._consumed = 0
        self._total: Optional[int] = None  # known once the reply lands
        self._buffer: Dict[int, bytes] = {}
        self._finished = False
        self.kind: Optional[str] = None    # "gen" | "value"
        self.value: Any = None

    def __iter__(self) -> "StreamingDeploymentResponse":
        return self

    def __next__(self) -> Any:
        import queue as _queue

        from ray_tpu._private import serialization

        deadline = time.monotonic() + self._chunk_timeout_s
        while True:
            if self._consumed in self._buffer:
                payload = self._buffer.pop(self._consumed)
                self._consumed += 1
                return serialization.loads(payload)
            try:
                seq, payload = self._queue.get(timeout=self._POLL_S)
                self._buffer[seq] = payload
                continue
            except _queue.Empty:
                pass
            try:
                self._check_final()
            except BaseException:
                self._finish()
                raise
            if self.kind == "value" or (
                    self._total is not None
                    and self._consumed >= self._total):
                self._finish()
                raise StopIteration
            if time.monotonic() > deadline:
                self._finish()
                raise TimeoutError(
                    f"no stream chunk within {self._chunk_timeout_s}s")

    def _check_final(self) -> None:
        """Adopt the replica's final reply once it is ready (non-blocking);
        raises the replica's error if the stream failed mid-generation."""
        if self.kind is not None:
            return
        import ray_tpu

        ready, _ = ray_tpu.wait([self._ref], timeout=0)
        if not ready:
            return
        kind, payload = ray_tpu.get(self._ref)
        if kind == "value":
            self.kind, self.value = "value", payload
        else:
            self.kind, self._total = "gen", int(payload)

    def first_event(self):
        """('chunk', item) | ('value', v) | ('end', None) — lets the HTTP
        proxy decide between a plain and a chunked response."""
        try:
            return ("chunk", next(self))
        except StopIteration:
            if self.kind == "value":
                return ("value", self.value)
            return ("end", None)

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        from ray_tpu._private.worker import global_worker

        if global_worker is not None:
            global_worker.close_stream(self._stream_id)
        self._router._complete(self._replica_tag)

    def __del__(self):
        try:
            self._finish()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class Router:
    """Caches the replica set for one deployment (refreshed from the
    controller on a version bump) and schedules requests pow-2 style."""

    _REFRESH_S = 1.0

    _METRICS_PUSH_S = 0.5

    def __init__(self, deployment_name: str, app_name: str):
        self._deployment = deployment_name
        self._app = app_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []  # (tag, ActorHandle)
        self._inflight: Dict[str, int] = {}
        self._last_refresh = 0.0
        self._handle_id = f"router-{id(self):x}"
        self._metrics_started = False
        self._stopped = False
        # admission control: per-replica in-flight is bounded at
        # max_ongoing + max_queued_requests (deployment config, fetched
        # with the replica set); the env knob overrides the queue part
        env_depth = os.environ.get("RAY_TPU_SERVE_MAX_QUEUE_DEPTH")
        self._env_queue_depth = (int(env_depth) if env_depth not in
                                 (None, "") else None)
        self._limits: Dict[str, Any] = {}
        self._limits_pending = False
        self._warned_default_bound = False

    def _controller(self):
        import ray_tpu
        return ray_tpu.get_actor(CONTROLLER_NAME)

    _router_gauge = None
    _router_gauge_lock = threading.Lock()

    @classmethod
    def _queue_depth_gauge(cls):
        """Process-wide router queue-depth gauge (queued+ongoing per
        handle, the same number the controller autoscales on), exported
        through the util.metrics Prometheus pipeline. Double-checked:
        unlocked fast path per push tick; the lock only guards the
        first registration so racing push loops of two handles cannot
        register duplicates."""
        if cls._router_gauge is not None:
            return cls._router_gauge
        with cls._router_gauge_lock:
            if cls._router_gauge is None:
                from ray_tpu.util.metrics import Gauge

                cls._router_gauge = Gauge(
                    "serve_router_queue_depth",
                    "requests queued+ongoing through this handle",
                    tag_keys=("app", "deployment", "handle"))
        return cls._router_gauge

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        with self._lock:
            stale = force or not self._replicas or \
                now - self._last_refresh > self._REFRESH_S
        if not stale:
            return
        import ray_tpu
        ctrl = self._controller()
        version, replicas = ray_tpu.get(
            ctrl.get_replicas.remote(self._app, self._deployment))
        limits = None
        with self._lock:
            need_limits = version != self._version or \
                self._limits_pending
        if need_limits:
            try:
                limits = ray_tpu.get(ctrl.get_deployment_limits.remote(
                    self._app, self._deployment))
            except Exception:  # noqa: BLE001 — pre-admission controller
                limits = None
        with self._lock:
            self._last_refresh = time.monotonic()
            if version != self._version:
                self._version = version
                self._replicas = list(replicas)
                self._inflight = {tag: self._inflight.get(tag, 0)
                                  for tag, _ in self._replicas}
            if need_limits:
                if limits is not None:
                    self._limits = dict(limits)
                    self._limits_pending = False
                else:
                    # transient controller failure must not disable
                    # admission control until the next redeploy —
                    # retry the fetch on the next refresh
                    self._limits_pending = True

    _PICK_TIMEOUT_S = 30.0

    def _shed_bound(self) -> Optional[int]:
        """Per-replica in-flight bound for admission control
        (max_ongoing + max_queued_requests), or None when shedding is
        disabled (max_queued_requests < 0 and no env override)."""
        lim = self._limits or {}
        queued = lim.get("max_queued_requests", -1)
        if self._env_queue_depth is not None:
            queued = self._env_queue_depth
        if queued is None or int(queued) < 0:
            return None
        if "max_ongoing_requests" not in lim:
            # limits fetch unavailable (pre-admission controller / RPC
            # failure): the replica's REAL capacity is unknown. If only
            # the deployment config asked for shedding, leave it off
            # until the retried fetch (_limits_pending) lands — guessing
            # would shed healthy capacity on any deployment sized above
            # the default. But an explicit env knob is an operator
            # demanding admission control NOW: honor it against the
            # config default rather than silently queueing unboundedly,
            # and say which capacity was assumed.
            if self._env_queue_depth is None:
                return None
            from .config import DeploymentConfig

            ongoing = DeploymentConfig().max_ongoing_requests
            if not self._warned_default_bound:
                self._warned_default_bound = True
                logger.warning(
                    "RAY_TPU_SERVE_MAX_QUEUE_DEPTH is set but %s#%s's "
                    "limits are not available from the controller — "
                    "shedding against the default max_ongoing_requests "
                    "(%d) until the limits fetch succeeds",
                    self._app, self._deployment, ongoing)
            return ongoing + int(queued)
        return int(lim["max_ongoing_requests"]) + int(queued)

    def _raise_shed(self, bound: int) -> None:
        from ray_tpu.util import envknobs

        retry = envknobs.get_float("RAY_TPU_SERVE_RETRY_AFTER_S", 1.0)
        shed_counter().inc(tags={"app": self._app,
                                 "deployment": self._deployment})
        raise RequestShedError(
            f"deployment {self._app}#{self._deployment}: every replica "
            f"is at its in-flight bound ({bound}); retry after "
            f"{retry:.1f}s", retry_after_s=retry)

    _SHED = object()  # _try_pick sentinel: every replica at its bound

    def _try_pick(self, bound: Optional[int] = None):
        """One non-blocking pow-2 choice; None when no replicas are
        known. The admission bound is enforced UNDER the same lock as
        the in-flight reservation (check-then-act would let N racing
        callers all pass a separate shed check before any increments,
        making max_queued_requests advisory): candidates are the
        replicas still under `bound`, and when there are none the
        `_SHED` sentinel is returned for the caller to raise on outside
        the lock. On success the replica's in-flight count is already
        incremented. An empty replica set defers to the pick wait (a
        deploying app is not overload)."""
        with self._lock:
            if not self._replicas:
                return None
            cands = self._replicas
            if bound is not None:
                cands = [r for r in self._replicas
                         if self._inflight.get(r[0], 0) < bound]
                if not cands:
                    return self._SHED
            if len(cands) == 1:
                chosen = cands[0]
            else:
                a, b = random.sample(cands, 2)
                chosen = a if self._inflight.get(a[0], 0) <= \
                    self._inflight.get(b[0], 0) else b
            self._inflight[chosen[0]] = \
                self._inflight.get(chosen[0], 0) + 1
            return chosen

    def _no_replica_error(self) -> TimeoutError:
        return TimeoutError(
            f"no running replicas for deployment "
            f"{self._app}#{self._deployment} after "
            f"{self._PICK_TIMEOUT_S:.0f}s")

    def _pick(self, bound: Optional[int] = None) -> Tuple[str, Any]:
        deadline = time.monotonic() + self._PICK_TIMEOUT_S
        while True:
            self._refresh()
            chosen = self._try_pick(bound)
            if chosen is self._SHED:
                self._raise_shed(bound)
            if chosen is not None:
                return chosen
            if time.monotonic() > deadline:
                raise self._no_replica_error()
            # The wait below blocks this thread. On an event-loop thread
            # that would freeze EVERY coroutine on the loop for up to 30s
            # (shardlint blocking-in-async) — fail fast with the async
            # alternative instead of silently wedging the replica.
            try:
                import asyncio

                asyncio.get_running_loop()
            except RuntimeError:
                pass
            else:
                raise RuntimeError(
                    f"no replica of {self._app}#{self._deployment} is "
                    "available and the blocking wait would stall this "
                    "thread's running event loop; use `await "
                    "handle.remote_async(...)` from async code, or "
                    "offload the call with loop.run_in_executor")
            time.sleep(0.1)

    async def _pick_async(self, bound: Optional[int] = None
                          ) -> Tuple[str, Any]:
        """Async pick: the controller refresh (a blocking RPC) runs on
        the default executor and the no-replica wait is an
        `await asyncio.sleep`, so the caller's event loop keeps serving
        other requests while this one waits for a replica."""
        import asyncio

        loop = asyncio.get_running_loop()
        deadline = time.monotonic() + self._PICK_TIMEOUT_S
        while True:
            await loop.run_in_executor(None, self._refresh)
            chosen = self._try_pick(bound)
            if chosen is self._SHED:
                self._raise_shed(bound)
            if chosen is not None:
                return chosen
            if time.monotonic() > deadline:
                raise self._no_replica_error()
            await asyncio.sleep(0.1)

    def _complete(self, tag: str):
        with self._lock:
            if tag in self._inflight and self._inflight[tag] > 0:
                self._inflight[tag] -= 1

    def _start_metrics_push(self):
        """Handle-side autoscaling metrics — reference serve/_private/
        router.py pushes num_queued+ongoing per handle to the controller
        (autoscaling_state.py); replica-side probes would deadlock behind a
        saturated replica's own request pool."""
        with self._lock:
            if self._metrics_started:
                return
            self._metrics_started = True

        def push_loop():
            while not self._stopped:
                time.sleep(self._METRICS_PUSH_S)
                try:
                    with self._lock:
                        total = sum(self._inflight.values())
                    self._queue_depth_gauge().set(
                        total, tags={"app": self._app,
                                     "deployment": self._deployment,
                                     "handle": self._handle_id})
                    self._controller().record_handle_metrics.remote(
                        self._app, self._deployment, self._handle_id, total)
                except Exception:  # noqa: BLE001 — controller restarting
                    pass

        threading.Thread(target=push_loop, daemon=True,
                         name="serve-handle-metrics").start()

    def assign(self, meta: RequestMetadata, args, kwargs,
               retries: int = 2) -> DeploymentResponse:
        self._start_metrics_push()
        self._refresh()
        bound = self._shed_bound()
        last_err: Optional[Exception] = None
        for _ in range(retries + 1):
            tag, handle = self._pick(bound)
            try:
                ref = handle.handle_request.remote(
                    meta.to_dict(), list(args), dict(kwargs))
                return DeploymentResponse(ref, self, tag,
                                          request=(meta, args, kwargs))
            except Exception as e:  # noqa: BLE001 — dead replica: drop + retry
                last_err = e
                self._complete(tag)
                self._refresh(force=True)
        raise last_err  # type: ignore[misc]

    async def assign_async(self, meta: RequestMetadata, args, kwargs,
                           retries: int = 2) -> DeploymentResponse:
        """Async twin of assign() for callers already on an event loop
        (async deployment methods composing other deployments): picking
        waits with `await asyncio.sleep` and the submit RPC runs on the
        default executor, so the loop never blocks."""
        import asyncio

        loop = asyncio.get_running_loop()
        self._start_metrics_push()
        await loop.run_in_executor(None, self._refresh)
        bound = self._shed_bound()
        last_err: Optional[Exception] = None
        for _ in range(retries + 1):
            tag, handle = await self._pick_async(bound)
            try:
                ref = await loop.run_in_executor(
                    None, lambda: handle.handle_request.remote(
                        meta.to_dict(), list(args), dict(kwargs)))
                return DeploymentResponse(ref, self, tag,
                                          request=(meta, args, kwargs))
            except Exception as e:  # noqa: BLE001 — dead replica: retry
                last_err = e
                self._complete(tag)
                await loop.run_in_executor(
                    None, lambda: self._refresh(force=True))
        raise last_err  # type: ignore[misc]

    def assign_stream(self, meta: RequestMetadata, args, kwargs,
                      retries: int = 2) -> StreamingDeploymentResponse:
        """Streaming variant of assign: opens a local stream endpoint the
        replica pushes chunks at (reference router streaming path)."""
        from ray_tpu._private.worker import global_worker

        self._start_metrics_push()
        self._refresh()
        bound = self._shed_bound()
        last_err: Optional[Exception] = None
        for _ in range(retries + 1):
            tag, handle = self._pick(bound)
            stream_id, q = global_worker.open_stream()
            try:
                ref = handle.handle_request_streaming.remote(
                    meta.to_dict(), list(args), dict(kwargs), stream_id,
                    tuple(global_worker.address))
                return StreamingDeploymentResponse(ref, self, tag,
                                                   stream_id, q)
            except Exception as e:  # noqa: BLE001 — dead replica: retry
                global_worker.close_stream(stream_id)
                last_err = e
                self._complete(tag)
                self._refresh(force=True)
        raise last_err  # type: ignore[misc]


# One Router per (app, deployment) per process — shared across all handles
# (including the throwaway ones __getattr__/options() mint), so pow-2
# in-flight state is coherent and only one metrics thread exists per target.
_ROUTERS: Dict[Tuple[str, str], Router] = {}
_ROUTERS_LOCK = threading.Lock()


def _shared_router(deployment_name: str, app_name: str) -> Router:
    key = (app_name, deployment_name)
    with _ROUTERS_LOCK:
        r = _ROUTERS.get(key)
        if r is None:
            r = Router(deployment_name, app_name)
            _ROUTERS[key] = r
        return r


def _drop_routers(app_name: Optional[str] = None) -> None:
    """Forget cached routers (on serve.shutdown/delete) so a later
    redeploy doesn't serve stale replica sets."""
    with _ROUTERS_LOCK:
        for key in [k for k in _ROUTERS
                    if app_name is None or k[0] == app_name]:
            _ROUTERS[key]._stopped = True  # ends its metrics thread
            del _ROUTERS[key]


class DeploymentHandle:
    """Picklable handle to a deployment — reference serve/handle.py:711.
    ``handle.method.remote(*args)`` returns a DeploymentResponse."""

    def __init__(self, deployment_name: str, app_name: str = "default",
                 _call_method: str = "__call__",
                 _multiplexed_model_id: str = "", _stream: bool = False):
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._call_method = _call_method
        self._multiplexed_model_id = _multiplexed_model_id
        self._stream = _stream

    @property
    def _router(self) -> Router:
        return _shared_router(self.deployment_name, self.app_name)

    def options(self, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.deployment_name, self.app_name,
            _call_method=method_name or self._call_method,
            _multiplexed_model_id=(multiplexed_model_id
                                   if multiplexed_model_id is not None
                                   else self._multiplexed_model_id),
            _stream=self._stream if stream is None else stream)

    def _request(self, args, kwargs):
        meta = RequestMetadata(
            call_method=self._call_method,
            multiplexed_model_id=self._multiplexed_model_id,
            app_name=self.app_name)
        args = tuple(a._to_object_ref() if isinstance(a, DeploymentResponse)
                     else a for a in args)
        kwargs = {k: (v._to_object_ref() if isinstance(v, DeploymentResponse)
                      else v) for k, v in kwargs.items()}
        return meta, args, kwargs

    def remote(self, *args, **kwargs):
        meta, args, kwargs = self._request(args, kwargs)
        if self._stream:
            return self._router.assign_stream(meta, args, kwargs)
        return self._router.assign(meta, args, kwargs)

    async def remote_async(self, *args, **kwargs) -> DeploymentResponse:
        """Loop-safe `remote()` for async deployment methods: awaiting it
        never blocks the event loop, even while waiting for a replica to
        come up (the sync path refuses to poll-wait on a loop thread)."""
        meta, args, kwargs = self._request(args, kwargs)
        if self._stream:
            raise NotImplementedError(
                "remote_async does not support stream=True handles yet; "
                "use options(stream=True).remote() from a worker thread")
        return await self._router.assign_async(meta, args, kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return self.options(method_name=name)

    def __reduce__(self):
        return (DeploymentHandle,
                (self.deployment_name, self.app_name, self._call_method,
                 self._multiplexed_model_id, self._stream))

    def __repr__(self):
        return (f"DeploymentHandle(deployment='{self.deployment_name}', "
                f"app='{self.app_name}')")
