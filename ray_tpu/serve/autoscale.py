"""SLO-driven autoscaler for (disaggregated) serving: close the
control loop.

Every input the loop needs already exists — per-replica TTFT telemetry
(PR 3/6), bounded-queue admission with shed counters and live queue
depths (PR 9), and the preemption grace/drain flow (PR 4) — this module
adds the POLICY that turns them into replica counts. The Gemma-on-TPU
serving envelope (PAPERS.md: arXiv 2605.25645) frames what "enough
replicas" means; the TPU concurrency-limits roofline (arXiv 2011.03641)
is why prefill and decode saturate on DIFFERENT signals and must scale
independently:

- **prefill** is compute-bound burst work: its saturation shows up as
  queueing delay ahead of the first token — recent p99 TTFT against the
  target SLO — discounted by the prefix-cache hit rate (a hit-heavy
  window prefills only suffixes and needs fewer prefill chips).
- **decode** is memory-bound steady work: its saturation is free-slot
  exhaustion — when the tier's decode slots run out, admission control
  starts queueing and then shedding, long before prefill notices.

Pieces (each independently testable, no cluster required):

- ``SlidingWindow``: trailing-window samples -> recent p50/p99 summary
  (the shared ``step_timer.percentile``), so the policy reads *recent*
  percentiles, not lifetime-cumulative ones that lag load shifts.
- ``ScalingPolicy``: the hysteresis + cooldown core — desired-vs-current
  persistence gates (scale up only after the pressure held for
  ``up_delay_s``, down after ``down_delay_s``, nothing within
  ``cooldown_s`` of the last change) — shared by the disagg loop AND the
  generic Serve controller's reconcile tick (serve/controller.py), so
  one engine owns "don't flap" everywhere.
- ``DisaggPolicy``: maps a signals snapshot to desired per-tier counts.
- ``DisaggAutoscaler``: the loop. Scale-up builds a replica via the
  tier's factory and registers it with the router — new replicas admit
  immediately. Scale-down REUSES the graceful-drain flow: the router
  stops dispatching to the victim (``begin_drain``) while its in-flight
  requests finish and its KV transfers are acked, then
  ``prepare_for_shutdown`` (the replica-side grace drain, the same
  shape as serve/replica.py and the preemption grace window) runs
  before the actor dies — an in-flight request is NEVER dropped by a
  scale-down.

The loop also owns **tier self-healing** (the serving-plane complement
of PR 4's gang supervision): once started it subscribes to the
conductor's actor-death pubsub for its managed replicas. A death is NOT
load — it bypasses the hysteresis/cooldown machinery entirely: the
corpse is removed from the router immediately (distinct from a drain —
no grace, its in-flight requests already failed over at the router) and
a replacement is spawned through the tier's ``TierSpec.factory``. A
per-host circuit breaker (the existing
``resilience.domains.FailureDomainTracker``, threshold
``RAY_TPU_SERVE_BREAKER_THRESHOLD`` deaths decaying over
``RAY_TPU_SERVE_BREAKER_WINDOW_S``) stops replacing replicas that die
repeatedly on the same host — replacing into a bad host only
manufactures failures — and a replica that dies MID-DRAIN is reaped
and its drain record finalized instead of leaking a ``draining`` entry
forever. ``replace`` / ``breaker_trip`` markers land in the merged
timeline's resilience lane beside the router's ``failover`` markers,
and per-tier ``replacements_total`` counters feed the servefault
surface.

Surfaces (the full treatment): ``util.state.autoscaler_status()``,
``ray_tpu autoscale`` CLI, dashboard ``/api/autoscale`` + SPA tab, lazy
Prometheus (``ray_tpu_autoscale_target_replicas{tier}``,
``ray_tpu_autoscale_decisions_total{tier,direction}``,
``ray_tpu_autoscale_replica_seconds_total{tier}``), and scale_up /
scale_down / drain instant markers in the merged timeline (drains are
mirrored into the resilience lane — they ARE the grace flow).

Knobs (env, all overridable per-instance): RAY_TPU_AUTOSCALE_TARGET_P99_MS
(the SLO), RAY_TPU_AUTOSCALE_UP_DELAY_S / _DOWN_DELAY_S / _COOLDOWN_S
(hysteresis), RAY_TPU_AUTOSCALE_INTERVAL_S (tick), _DRAIN_GRACE_S (the
drain window), _WINDOW_S (signal recency). The acceptance benchmark is
``python -m ray_tpu.bench_serve --autoscale --compare-static``.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.observability.step_timer import percentile

_SEQ = itertools.count()

TIERS = ("prefill", "decode")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def default_target_p99_ms() -> float:
    """The serving SLO the loop closes on (recent p99 TTFT, ms)."""
    return _env_float("RAY_TPU_AUTOSCALE_TARGET_P99_MS", 1500.0)


# ----------------------------------------------------- prometheus (lazy)

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def autoscale_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                target=Gauge(
                    "ray_tpu_autoscale_target_replicas",
                    "replica count the autoscaler is currently driving "
                    "a tier toward",
                    tag_keys=("tier",)),
                decisions=Counter(
                    "ray_tpu_autoscale_decisions_total",
                    "scale decisions taken (direction=up|down)",
                    tag_keys=("tier", "direction")),
                replica_seconds=Counter(
                    "ray_tpu_autoscale_replica_seconds_total",
                    "cumulative live replica-seconds per tier (the "
                    "provisioning cost the policy is minimizing)",
                    tag_keys=("tier",)))
    return _metrics


# --------------------------------------------------------- sliding window

class SlidingWindow:
    """Trailing-window scalar samples -> recent summary.

    The policy (and `serve status` / router stats) must read RECENT
    percentiles: a lifetime-cumulative histogram still remembers the
    morning's quiet hours at the evening peak. Samples older than
    ``window_s`` age out; ``max_samples`` bounds memory under a flood.
    Percentiles come from the shared ``step_timer.percentile`` so every
    recent-p99 in the system is the same derivation."""

    def __init__(self, window_s: Optional[float] = None,
                 max_samples: int = 2048):
        if window_s is None:
            window_s = _env_float("RAY_TPU_AUTOSCALE_WINDOW_S", 30.0)
        self.window_s = float(window_s)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._samples: List[Tuple[float, float]] = []  # (ts, value)

    def add(self, value: float, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            self._samples.append((now, float(value)))
            if len(self._samples) > self.max_samples:
                del self._samples[:len(self._samples) - self.max_samples]

    def _values(self, now: Optional[float]) -> List[float]:
        now = time.monotonic() if now is None else now
        horizon = now - self.window_s
        with self._lock:
            # prune in place so a long-lived idle window frees its tail
            i = 0
            while i < len(self._samples) and self._samples[i][0] < horizon:
                i += 1
            if i:
                del self._samples[:i]
            return [v for _, v in self._samples]

    def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
        """{"n", "mean", "p50", "p99", "last"} over the live window
        ({"n": 0} when empty — callers treat missing signals as
        no-evidence, never as zero)."""
        vals = self._values(now)
        if not vals:
            return {"n": 0}
        ordered = sorted(vals)
        return {"n": len(vals),
                "mean": sum(vals) / len(vals),
                "p50": percentile(ordered, 0.5),
                "p99": percentile(ordered, 0.99),
                "last": vals[-1]}


# --------------------------------------------------------- policy engine

class ScalingPolicy:
    """Hysteresis + cooldown around a desired-replicas signal.

    Semantics (lifted from serve/controller.py's reconcile tick, now THE
    shared engine): the clock toward scaling up runs only while
    desired > current — any tick at-or-below resets it (and vice versa
    for down) — so a transient burst never scales and an oscillating
    signal never flaps; ``cooldown_s`` additionally freezes the tier
    after any change so back-to-back moves can't chase noise."""

    def __init__(self, min_replicas: int = 1, max_replicas: int = 4,
                 up_delay_s: Optional[float] = None,
                 down_delay_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None):
        if up_delay_s is None:
            up_delay_s = _env_float("RAY_TPU_AUTOSCALE_UP_DELAY_S", 2.0)
        if down_delay_s is None:
            down_delay_s = _env_float("RAY_TPU_AUTOSCALE_DOWN_DELAY_S",
                                      10.0)
        if cooldown_s is None:
            cooldown_s = _env_float("RAY_TPU_AUTOSCALE_COOLDOWN_S", 5.0)
        if max_replicas < max(1, min_replicas):
            raise ValueError(
                f"invalid replica bounds [{min_replicas}, {max_replicas}]")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_delay_s = float(up_delay_s)
        self.down_delay_s = float(down_delay_s)
        self.cooldown_s = float(cooldown_s)
        # last instant the tier was NOT under up/down pressure — the
        # persistence gate measures from here (None until the first
        # decide() so injected clocks and the real one never mix)
        self._calm_up: Optional[float] = None
        self._calm_down: Optional[float] = None
        self._last_change: Optional[float] = None

    def clamp(self, n: int) -> int:
        return min(max(int(n), self.min_replicas), self.max_replicas)

    def decide(self, desired: int, current: int,
               now: Optional[float] = None) -> int:
        """The new target (== current when the gates hold it back)."""
        now = time.monotonic() if now is None else now
        desired = self.clamp(desired)
        if self._calm_up is None:
            self._calm_up = self._calm_down = now
        if desired <= current:
            self._calm_up = now       # not under scale-up pressure
        if desired >= current:
            self._calm_down = now     # not over-provisioned
        in_cooldown = (self._last_change is not None
                       and now - self._last_change < self.cooldown_s)
        if desired > current and not in_cooldown \
                and now - self._calm_up >= self.up_delay_s:
            self._last_change = now
            self._calm_up = self._calm_down = now
            return desired
        if desired < current and not in_cooldown \
                and now - self._calm_down >= self.down_delay_s:
            self._last_change = now
            self._calm_up = self._calm_down = now
            return desired
        return current


class DisaggPolicy:
    """Signals -> desired replica counts, one tier at a time.

    The signals snapshot (``DisaggRouter.signals()`` + per-tick
    free-slot probes; every key optional — missing evidence never
    scales):

    - ``ttft_p99_ms``: recent p99 TTFT (router sliding window). Under
      disaggregation TTFT ends when prefill returns the first token, so
      this IS the prefill queueing-delay signal.
    - ``cache_hit_rate``: recent fraction of prefills served fully or
      partially from the prefix cache. A hit-heavy window prefills only
      suffixes — scale-down of the prefill tier is gated on it (or on
      the tier being outright idle).
    - ``prefill_inflight_p99``: recent concurrent prefills — the
      does-it-fit-in-one-fewer check for prefill scale-down.
    - ``decode_free_p50`` / ``decode_busy_p99``: recent free and busy
      decode slots across the tier; ``decode_cap_per_replica`` sizes
      what one fewer replica could still hold.
    - ``queue_depth_p99``: recent router pending — backlog past the
      decode tier's capacity also reads as slot exhaustion (sheds live
      at that same bound).
    - ``spec_tokens_per_verify``: measured speculative-decoding
      acceptance factor (mean tokens emitted per verify step across the
      decode tier, from the engines' speculation_stats). A tier whose
      engines emit ~f tokens per step drains a BACKLOG f× faster, so
      queued demand is discounted by it before the policy sizes the
      tier — busy slots are not (speculation shortens a stream, it
      does not free the slot it occupies). Absent (or <= 1) means no
      discount: behavior is bit-identical to a non-speculative tier.
    """

    # scale down only when the recent p99 fits inside one-fewer replicas
    # at this utilization — the headroom that makes drain safe
    low_util = 0.7
    # prefill scale-down additionally wants the SLO comfortably met
    down_ratio = 0.5
    # ...and a hit-heavy cache (or an idle tier): hit windows need fewer
    # prefill chips even at the same request rate
    hit_floor = 0.5

    def __init__(self, target_p99_ms: Optional[float] = None,
                 prefill_policy: Optional[ScalingPolicy] = None,
                 decode_policy: Optional[ScalingPolicy] = None):
        self.target_p99_ms = (default_target_p99_ms()
                              if target_p99_ms is None
                              else float(target_p99_ms))
        self.policies = {"prefill": prefill_policy or ScalingPolicy(),
                         "decode": decode_policy or ScalingPolicy()}

    # -- desired (pure; no hysteresis — ScalingPolicy applies that) ------

    def desired_decode(self, signals: Dict[str, Any],
                       current: int) -> Tuple[int, str]:
        free_p50 = signals.get("decode_free_p50")
        busy_p99 = signals.get("decode_busy_p99")
        depth_p99 = signals.get("queue_depth_p99")
        cap = max(1, int(signals.get("decode_cap_per_replica", 1)))
        capacity = current * cap
        # speculation-aware demand: f tokens emitted per verify step
        # means each slot drains its queued successor f× sooner, so a
        # backlog of N requests is N/f slot-windows of work. Only the
        # QUEUE is discounted — an occupied slot is occupied whatever
        # its token rate. f <= 1 (or no signal) leaves every number
        # untouched, so a non-speculative tier is bit-identical.
        spec = signals.get("spec_tokens_per_verify")
        factor = max(1.0, float(spec or 0.0))
        eff_depth = (depth_p99 / factor
                     if depth_p99 is not None else None)
        if eff_depth is not None and eff_depth > capacity:
            # PROPORTIONAL scale step for deep backlogs (the PR-11
            # follow-on): ±1 per decision chases a burst one cooldown
            # at a time — when the backlog exceeds 2x one replica's
            # capacity, jump straight to the replica count that holds
            # it (ceil(backlog / capacity_per_replica); TierSpec
            # bounds clamp at apply time, hysteresis still gates)
            desired = current + 1
            if eff_depth > 2 * cap:
                desired = max(desired, -(-int(eff_depth) // cap))
            return desired, (
                f"backlog p99 {depth_p99:.0f}"
                + (f" (/{factor:.2f} speculation -> {eff_depth:.0f})"
                   if factor > 1.0 else "")
                + f" past tier capacity {capacity}"
                + (f" (proportional step -> {desired})"
                   if desired > current + 1 else ""))
        if free_p50 is not None and free_p50 <= 0:
            return current + 1, "decode slots exhausted (free p50 = 0)"
        # slot DEMAND, not just engine-busy slots: a slow client drains
        # its stream long after the engine slot freed, but it still
        # occupies the router's admission bound — the thing a removed
        # replica would shrink. Take the worse of the two recent views.
        # current > 0 (not > 1): at current == 1 the condition reduces
        # to demand == 0, i.e. a truly idle tier may drain to ZERO —
        # the ScalingPolicy's min_replicas floor (1 everywhere except
        # an explicit scale-to-zero tier) clamps it back otherwise.
        demand = max((v for v in (busy_p99, eff_depth)
                      if v is not None), default=None)
        if current > 0 and demand is not None \
                and demand <= self.low_util * (current - 1) * cap:
            return current - 1, (
                f"slot demand p99 {demand:.1f} fits in {current - 1} "
                f"replica(s) at {self.low_util:.0%} utilization")
        return current, "steady"

    def desired_prefill(self, signals: Dict[str, Any],
                        current: int) -> Tuple[int, str]:
        ttft_p99 = signals.get("ttft_p99_ms")
        hit_rate = signals.get("cache_hit_rate")
        inflight_p99 = signals.get("prefill_inflight_p99")
        if current > 0 and ttft_p99 is None and inflight_p99 is None:
            # missing evidence never scales UP — but for a tier above
            # its floor, a request window with no samples at all IS the
            # evidence: nothing has needed prefill for a whole window
            # (current > 0 so a scale-to-zero tier drains its last
            # replica on the same evidence; min_replicas clamps
            # everyone else at 1)
            return current - 1, "tier idle (no requests in the window)"
        if ttft_p99 is not None and ttft_p99 > self.target_p99_ms:
            return current + 1, (
                f"TTFT p99 {ttft_p99:.0f}ms over target "
                f"{self.target_p99_ms:.0f}ms (queueing delay)")
        if current > 1 and ttft_p99 is not None \
                and ttft_p99 < self.down_ratio * self.target_p99_ms:
            hit_heavy = hit_rate is not None and hit_rate >= self.hit_floor
            idle = inflight_p99 is not None and \
                inflight_p99 <= self.low_util * (current - 1)
            # a hit-heavy window needs fewer prefill chips; an idle tier
            # trivially does — either way the SLO is comfortably met
            if hit_heavy or idle:
                why = (f"hit rate {hit_rate:.0%} — suffix-only prefills"
                       if hit_heavy else
                       f"inflight p99 {inflight_p99:.1f} fits in "
                       f"{current - 1}")
                return current - 1, (
                    f"TTFT p99 {ttft_p99:.0f}ms well under target; {why}")
        return current, "steady"

    def decide(self, signals: Dict[str, Any], current: Dict[str, int],
               now: Optional[float] = None
               ) -> Dict[str, Tuple[int, str]]:
        """{tier: (target, reason)} after hysteresis; target == current
        means hold."""
        out: Dict[str, Tuple[int, str]] = {}
        for tier, fn in (("prefill", self.desired_prefill),
                         ("decode", self.desired_decode)):
            cur = int(current[tier])
            desired, reason = fn(signals, cur)
            target = self.policies[tier].decide(desired, cur, now)
            out[tier] = (target, reason if target != cur else "hold")
        return out


# ----------------------------------------------------------- the loop

def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def _notify_event(event: Dict[str, Any]) -> None:
    """Best-effort instant marker into the conductor's autoscale event
    log (the merged timeline's `autoscale` lane)."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_autoscale_event", dict(event))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _notify_resilience(event: Dict[str, Any]) -> None:
    """Drains ride the resilience grace flow — mirror them into its
    event log/counters too (the PR-4 lane preemptions already use)."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_resilience_event", dict(event))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


class TierSpec:
    """How one tier scales: bounds plus the factory that builds a fresh
    replica (in-process object or actor handle — the router accepts
    either; the autoscaler tears actors down with kill after the grace
    drain)."""

    def __init__(self, factory: Callable[[], Any], *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 up_delay_s: Optional[float] = None,
                 down_delay_s: Optional[float] = None,
                 cooldown_s: Optional[float] = None):
        self.factory = factory
        self.policy = ScalingPolicy(min_replicas, max_replicas,
                                    up_delay_s, down_delay_s, cooldown_s)


class _Draining:
    __slots__ = ("tier", "rid", "since", "grace_deadline")

    def __init__(self, tier: str, rid: str, since: float, grace_s: float):
        self.tier = tier
        self.rid = rid
        self.since = since
        self.grace_deadline = since + grace_s


class DisaggAutoscaler:
    """Drives a ``DisaggRouter``'s prefill/decode replica sets toward
    the TTFT SLO. One ``tick()`` = read signals, decide, apply; the
    background thread just calls tick on ``interval_s``. Fully
    synchronous and injectable (``now`` flows through) so tests replay
    load shapes without sleeping."""

    def __init__(self, router: Any, *,
                 prefill: TierSpec, decode: TierSpec,
                 policy: Optional[DisaggPolicy] = None,
                 interval_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 autoscaler_id: Optional[str] = None):
        if not router.tier_replicas("prefill") \
                or not router.tier_replicas("decode"):
            raise ValueError("the autoscaler drives disagg routers "
                             "(a prefill AND a decode tier); colocated "
                             "deployments autoscale via the Serve "
                             "controller's AutoscalingConfig")
        self.router = router
        self.specs = {"prefill": prefill, "decode": decode}
        self.policy = policy or DisaggPolicy(
            prefill_policy=prefill.policy, decode_policy=decode.policy)
        self.interval_s = (interval_s if interval_s is not None else
                           _env_float("RAY_TPU_AUTOSCALE_INTERVAL_S", 1.0))
        self.drain_grace_s = (
            drain_grace_s if drain_grace_s is not None else
            _env_float("RAY_TPU_AUTOSCALE_DRAIN_GRACE_S", 30.0))
        self.autoscaler_id = autoscaler_id or \
            f"autoscale-{os.getpid()}-{next(_SEQ)}"
        self._free_win = SlidingWindow()
        self._busy_win = SlidingWindow()
        self._lock = threading.Lock()
        self._draining: List[_Draining] = []
        self._stats: Dict[str, Any] = {
            "scale_ups": {t: 0 for t in TIERS},
            "scale_downs": {t: 0 for t in TIERS},
            "drains_completed": 0,
            "drains_forced": 0,
            "drains_reaped": 0,
            "replica_seconds": {t: 0.0 for t in TIERS},
            "last_reason": {t: "" for t in TIERS},
            "deaths": {t: 0 for t in TIERS},
            "replacements": {t: 0 for t in TIERS},
            "replacements_blocked": 0,
            "breaker_trips": 0,
            "wakeups": {t: 0 for t in TIERS},
        }
        # scale-to-zero (min_replicas=0 on a TierSpec): an idle tier
        # drains to ZERO replicas, and the router calls the waker on
        # the first arrival — an immediate factory scale-up OUTSIDE
        # hysteresis (absence is not load), single-flight per tier
        self._waking: Dict[str, bool] = {t: False for t in TIERS}
        if any(self.specs[t].policy.min_replicas == 0 for t in TIERS):
            router.set_tier_waker(self._wake_tier)
        # the replacement circuit breaker: the existing failure-domain
        # tracker keyed by the replicas' HOST (machine id) — a host
        # whose replicas die repeatedly trips the latch and stops
        # getting replacements until the decayed score releases it
        from ray_tpu.resilience.domains import FailureDomainTracker

        self._breaker = FailureDomainTracker(
            threshold=_env_float("RAY_TPU_SERVE_BREAKER_THRESHOLD", 3.0),
            half_life_s=_env_float("RAY_TPU_SERVE_BREAKER_WINDOW_S",
                                   60.0))
        self._watching = False
        # actor_id -> (tier, {"rid", "machine"}) for every ACTOR
        # replica under management. Kept eagerly (watch/tick/add): by
        # the time a death event arrives, the router's failover wrapper
        # may already have removed the corpse from the replica set, so
        # the death must resolve against what we KNEW, not what's left.
        self._managed: Dict[str, Tuple[str, Dict[str, Any]]] = {}
        self._heals: List[threading.Thread] = []
        self._last_tick: Optional[float] = None
        self._last_push = 0.0
        self._last_sf_push = 0.0
        self._teardowns: List[threading.Thread] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        autoscale_metrics()  # lazy registration before the first event

    # ------------------------------------------------------------ signals

    def probe_signals(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Router windows + a live free-slot probe of the active decode
        replicas (folded into this loop's own sliding windows so one
        slow probe doesn't blind the policy)."""
        from .disagg import _call

        sig = self.router.signals()
        reps = [r for r in self.router.tier_replicas("decode")
                if not r["draining"]]
        free = cap = 0
        ok = False
        # issue every probe BEFORE resolving any (the _admit_or_shed
        # pattern): N actor replicas answer concurrently instead of
        # serializing N round-trips into every control-loop tick
        probes = []
        for r in reps:
            try:
                # read-only probe, supervised by the except below
                probes.append((r, _call(r["target"], "free_slots",  # shardlint: disable=unsupervised-actor-call
                                        block=False)))
            except Exception:  # noqa: BLE001 — replica mid-restart
                pass
        for r, v in probes:
            try:
                from ray_tpu._private.object_store import ObjectRef

                if isinstance(v, ObjectRef):
                    import ray_tpu

                    v = ray_tpu.get(v)
                free += int(v)
                cap += int(r["cap"])
                ok = True
            except Exception:  # noqa: BLE001 — replica mid-restart
                pass
        if ok:
            self._free_win.add(free, now)
            self._busy_win.add(cap - free, now)
        free_sum = self._free_win.summary(now)
        busy_sum = self._busy_win.summary(now)
        if free_sum["n"]:
            sig["decode_free_p50"] = free_sum["p50"]
            sig["decode_busy_p99"] = busy_sum["p99"]
        if reps:
            sig["decode_cap_per_replica"] = max(
                1, int(sum(r["cap"] for r in reps) / len(reps)))
        # measured speculation acceptance factor: best-effort stats
        # probe of the same live replicas; replicas without speculation
        # (or test doubles without a stats surface) simply contribute
        # nothing and the policy sees no discount
        stat_probes = []
        for r in reps:
            try:
                stat_probes.append(_call(r["target"], "stats",  # shardlint: disable=unsupervised-actor-call
                                         block=False))
            except Exception:  # noqa: BLE001 — replica mid-restart
                pass
        tpv: List[float] = []
        for v in stat_probes:
            try:
                from ray_tpu._private.object_store import ObjectRef

                if isinstance(v, ObjectRef):
                    import ray_tpu

                    v = ray_tpu.get(v)
                sp = (v or {}).get("speculation") or {}
                if int(sp.get("spec_verify_ticks", 0)) > 0:
                    tpv.append(float(sp.get("tokens_per_verify", 0.0)))
            except Exception:  # noqa: BLE001 — replica mid-restart
                pass
        if tpv:
            sig["spec_tokens_per_verify"] = sum(tpv) / len(tpv)
        return sig

    # --------------------------------------------------------------- tick

    def tick(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One control-loop pass; returns the actions taken."""
        now = time.monotonic() if now is None else now
        actions: List[Dict[str, Any]] = []
        self._account_replica_seconds(now)
        self._advance_drains(now, actions)
        signals = self.probe_signals(now)
        current = {t: self._active_count(t) for t in TIERS}
        decisions = self.policy.decide(signals, current, now)
        m = autoscale_metrics()
        for tier in TIERS:
            target, reason = decisions[tier]
            # the TierSpec bounds are the authoritative capacity limits
            # — a caller-supplied policy (its own clamps, or a test
            # stand-in) must not scale past what the tier may hold
            target = self.specs[tier].policy.clamp(target)
            with self._lock:  # _stats is shared with the wake/death threads
                self._stats["last_reason"][tier] = reason
            m["target"].set(target, tags={"tier": tier})
            if target > current[tier]:
                actions.extend(self._scale_up(
                    tier, target - current[tier], target, reason))
            elif target < current[tier]:
                actions.extend(self._scale_down(
                    tier, current[tier] - target, target, reason, now))
        self.publish_telemetry(force=bool(actions))
        return actions

    def _active_count(self, tier: str) -> int:
        return sum(1 for r in self.router.tier_replicas(tier)
                   if not r["draining"])

    def _account_replica_seconds(self, now: float) -> None:
        if self._last_tick is not None:
            dt = max(0.0, now - self._last_tick)
            m = autoscale_metrics()
            for tier in TIERS:
                live = len(self.router.tier_replicas(tier))
                with self._lock:
                    self._stats["replica_seconds"][tier] += live * dt
                if live:
                    m["replica_seconds"].inc(live * dt,
                                             tags={"tier": tier})
        self._last_tick = now

    # ----------------------------------------------------------- scale up

    def _scale_up(self, tier: str, n: int, target: int,
                  reason: str) -> List[Dict[str, Any]]:
        actions = []
        for _ in range(n):
            try:
                replica = self.specs[tier].factory()
            except Exception as e:  # noqa: BLE001 — no capacity yet:
                # hold the target; the next tick retries
                with self._lock:
                    self._stats["last_reason"][tier] = (
                        f"scale-up blocked: {type(e).__name__}: {e}")
                break
            rid = (self.router.add_prefill(replica) if tier == "prefill"
                   else self.router.add_decode(replica))
            if self._watching:
                self._refresh_managed()
            with self._lock:
                self._stats["scale_ups"][tier] += 1
            autoscale_metrics()["decisions"].inc(
                tags={"tier": tier, "direction": "up"})
            ev = {"kind": "scale_up", "tier": tier, "replica": rid,
                  "to": target, "reason": reason,
                  "autoscaler": self.autoscaler_id}
            _notify_event(ev)
            actions.append(ev)
        return actions

    # ------------------------------------------------------ scale to zero

    def _wake_tier(self, tier: str) -> bool:
        """The router's first-arrival-to-an-empty-tier hook: spawn one
        replica through the tier factory NOW (no hysteresis, no
        cooldown — the request is already waiting on it), off the
        arrival's thread, single-flight per tier. Returns whether a
        wake is coming — the router only WAITS on a True answer; a
        False keeps the pre-existing empty-tier behavior (immediate
        shed / self-healer wait). ONLY a min_replicas=0 tier wakes
        this way: a tier with a floor is empty because its replicas
        DIED, and respawning it from the traffic path would bypass the
        self-healer's per-host circuit breaker — exactly the
        repeatedly-dying-host churn the breaker exists to stop."""
        if tier not in self.specs \
                or self.specs[tier].policy.min_replicas != 0:
            return False
        with self._lock:
            if self._waking.get(tier):
                return True  # a wake is already in flight
            self._waking[tier] = True

        def run() -> None:
            try:
                if self._active_count(tier) > 0:
                    return  # raced another wake / a tick scale-up
                try:
                    replica = self.specs[tier].factory()
                except Exception as e:  # noqa: BLE001 — no capacity
                    with self._lock:
                        self._stats["last_reason"][tier] = (
                            f"wake blocked: {type(e).__name__}: {e}")
                    return
                rid = (self.router.add_prefill(replica)
                       if tier == "prefill"
                       else self.router.add_decode(replica))
                if self._watching:
                    self._refresh_managed()
                with self._lock:
                    self._stats["wakeups"][tier] += 1
                autoscale_metrics()["decisions"].inc(
                    tags={"tier": tier, "direction": "up"})
                _notify_event({"kind": "scale_from_zero", "tier": tier,
                               "replica": rid,
                               "autoscaler": self.autoscaler_id})
                self.publish_telemetry(force=True)
            finally:
                with self._lock:
                    self._waking[tier] = False

        threading.Thread(target=run, daemon=True,
                         name=f"autoscale-wake-{tier}").start()
        return True

    # --------------------------------------------------------- scale down

    def _scale_down(self, tier: str, n: int, target: int, reason: str,
                    now: float) -> List[Dict[str, Any]]:
        """Begin draining the newest active replicas (never below the
        initial set's oldest — newest-first mirrors the Serve
        controller's pending-first scale-down). A min_replicas=0 tier
        may drain its LAST replica (allow_empty): the attached waker
        makes the empty tier serveable again on the next arrival."""
        actions = []
        allow_empty = self.specs[tier].policy.min_replicas == 0
        active = [r for r in self.router.tier_replicas(tier)
                  if not r["draining"]]
        for r in list(reversed(active))[:n]:
            if not self.router.begin_drain(tier, r["rid"],
                                           allow_empty=allow_empty):
                continue
            with self._lock:
                self._draining.append(
                    _Draining(tier, r["rid"], now, self.drain_grace_s))
                self._stats["scale_downs"][tier] += 1
            autoscale_metrics()["decisions"].inc(
                tags={"tier": tier, "direction": "down"})
            ev = {"kind": "drain", "tier": tier, "replica": r["rid"],
                  "to": target, "inflight": r["inflight"],
                  "grace_s": self.drain_grace_s, "reason": reason,
                  "autoscaler": self.autoscaler_id}
            _notify_event(ev)
            _notify_resilience({"kind": "serve_drain", "name": r["rid"],
                                "tier": tier,
                                "grace_s": self.drain_grace_s})
            actions.append(ev)
        return actions

    def _replica_drained(self, d: _Draining) -> bool:
        """The zero-drop condition: no in-flight left at the router AND
        — for a prefill replica — no unacked KV transfer still held. A
        prefill call returns long before the decode side fetches its
        KV, so router in-flight alone would let a drain kill chunks a
        decode replica is about to read."""
        from .disagg import _call

        if not self.router.drained(d.tier, d.rid):
            return False
        if d.tier != "prefill":
            return True
        rep = next((r for r in self.router.tier_replicas("prefill")
                    if r["rid"] == d.rid), None)
        if rep is None:
            return True
        try:
            # drain probe on a possibly-dead replica, supervised below
            return int(_call(rep["target"], "stats")  # shardlint: disable=unsupervised-actor-call
                       .get("held_transfers", 0)) == 0
        except Exception:  # noqa: BLE001 — replica already dead
            return True

    def _advance_drains(self, now: float,
                        actions: List[Dict[str, Any]]) -> None:
        """Finalize drains whose replica has nothing left in flight (or
        whose grace window expired — the replica-side
        prepare_for_shutdown still runs, off the tick thread, so even
        the forced path waits out stragglers up to its own timeout
        before the actor dies)."""
        with self._lock:
            pending = list(self._draining)
        still: List[_Draining] = []
        for d in pending:
            drained = self._replica_drained(d)
            if not drained and now < d.grace_deadline:
                still.append(d)
                continue
            self._finalize_drain(d, drained)
            ev = {"kind": "scale_down", "tier": d.tier,
                  "replica": d.rid, "drained": bool(drained),
                  "waited_s": round(now - d.since, 3),
                  "autoscaler": self.autoscaler_id}
            _notify_event(ev)
            actions.append(ev)
        finalized = [d for d in pending if d not in still]
        with self._lock:
            # drop only what this pass finalized: the death watcher may
            # have reaped records (and _scale_down added new ones) while
            # the drain probes above ran off-lock
            self._draining = [d for d in self._draining
                              if d not in finalized]

    def _finalize_drain(self, d: _Draining, drained: bool) -> None:
        replica = self.router.remove(d.tier, d.rid)
        with self._lock:
            # the teardown below kills the actor ON PURPOSE — its DEAD
            # event must not read as a death to heal
            self._managed = {aid: v for aid, v in self._managed.items()
                             if v[1]["rid"] != d.rid}
            self._stats["drains_completed" if drained
                        else "drains_forced"] += 1
        if replica is None:
            return
        # replica-side teardown runs OFF the tick thread: a forced
        # drain's shutdown window must not stall the control loop
        # during exactly the load spike that may follow a scale-down
        t = threading.Thread(
            target=self._shutdown_replica, args=(replica, drained),
            daemon=True, name=f"autoscale-teardown-{d.rid}")
        t.start()
        self._teardowns.append(t)
        self._teardowns = [x for x in self._teardowns if x.is_alive()]

    def _shutdown_replica(self, replica: Any, drained: bool) -> None:
        """The replica-side grace drain (serve/replica.py shape): wait
        out in-flight work / unacked transfers, stop the engine, then
        release the actor. Drained replicas return from the wait
        immediately; the FORCED path (router-side grace expired with
        requests still running) gets one final bounded window so a
        straggling stream isn't cut mid-token the instant the deadline
        passes."""
        from .disagg import _call

        grace = 5.0 if drained else min(self.drain_grace_s, 10.0)
        try:
            _call(replica, "prepare_for_shutdown", grace)
        except Exception:  # noqa: BLE001 — replica already dead
            pass
        remote = getattr(getattr(replica, "stats", None), "remote", None)
        if remote is not None:  # actor handle: release the process
            try:
                import ray_tpu

                ray_tpu.kill(replica)
            except Exception:  # noqa: BLE001 — already gone
                pass

    # ------------------------------------------------------- self-healing

    def watch(self) -> "DisaggAutoscaler":
        """Subscribe to the conductor's actor-death pubsub for the
        managed replicas (idempotent; ``start()`` calls it). Death
        handling is fully event-driven — it never waits for a tick."""
        if self._watching:
            return self
        self._refresh_managed()
        w = _worker()
        if w is not None:
            w.subscribe_channel("actor_state", self._on_actor_state)
            self._watching = True
        return self

    def _refresh_managed(self) -> None:
        """Snapshot actor_id -> replica identity for every managed
        ACTOR replica currently registered with the router."""
        seen = []
        for tier in TIERS:
            for r in self.router.tier_replicas(tier):
                aid = getattr(r.get("target"), "actor_id", None)
                if aid:
                    seen.append((aid, (tier, {
                        "rid": r["rid"],
                        "machine": r.get("machine")})))
        with self._lock:
            self._managed.update(seen)

    def unwatch(self) -> None:
        if not self._watching:
            return
        w = _worker()
        if w is not None:
            try:
                w.unsubscribe_channel("actor_state",
                                      self._on_actor_state)
            except Exception:  # noqa: BLE001 — worker shutting down
                pass
        self._watching = False

    def _on_actor_state(self, msg: Any) -> None:
        if not isinstance(msg, dict) or msg.get("state") != "DEAD":
            return
        with self._lock:
            found = self._managed.pop(msg.get("actor_id"), None)
        if found is None:
            return  # not one of ours (or a scale-down teardown we did)
        # handle OFF the pubsub dispatch thread: replacement runs the
        # factory (actor spawn + engine init + first compile)
        t = threading.Thread(
            target=self._handle_replica_death,
            args=(found[0], found[1]), daemon=True,
            name=f"autoscale-heal-{found[1]['rid']}")
        t.start()
        self._heals.append(t)
        self._heals = [x for x in self._heals if x.is_alive()]

    def _handle_replica_death(self, tier: str,
                              rep: Dict[str, Any]) -> None:
        """One dead managed replica: reap the corpse (and any drain
        record it dies holding), charge the breaker, replace through
        the tier factory unless the breaker is open. Death is NOT load
        — none of this goes through hysteresis or cooldown."""
        rid = rep["rid"]
        machine = rep.get("machine") or "unknown-host"
        self.router.remove_dead(tier, rid)
        was_draining = False
        with self._lock:
            self._stats["deaths"][tier] += 1
            still = [d for d in self._draining if d.rid != rid]
            was_draining = len(still) != len(self._draining)
            self._draining = still
            if was_draining:
                # the drain/death race: a replica that dies mid-drain
                # must finalize its drain record, not stay "draining"
                # forever
                self._stats["drains_reaped"] += 1
        death_ev = {"kind": "replica_death", "tier": tier,
                    "replica": rid, "machine": machine,
                    "was_draining": was_draining,
                    "autoscaler": self.autoscaler_id}
        _notify_event(death_ev)        # the autoscale lane
        _notify_resilience(dict(death_ev))  # the servefault event slice
        if was_draining:
            _notify_event({"kind": "scale_down", "tier": tier,
                           "replica": rid, "drained": False,
                           "reaped": True,
                           "autoscaler": self.autoscaler_id})
        # breaker: decayed per-host death score through the existing
        # failure-domain tracker. The OPEN edge comes from the
        # tracker's own trip counter (incremented under ITS lock
        # exactly once per transition); our lock serializes concurrent
        # heal threads so two same-instant deaths can't both read the
        # pre-trip count and double-report one edge.
        from .disagg import servefault_metrics

        with self._lock:
            before = self._breaker.trip_count(machine)
            self._breaker.record(machine, "replica_death",
                                 detail=f"{tier}:{rid}")
            tripped = self._breaker.trip_count(machine) > before
            if tripped:
                self._stats["breaker_trips"] += 1
        if tripped:
            servefault_metrics()["breaker_trips"].inc()
            _notify_resilience({"kind": "breaker_trip", "host": machine,
                                "tier": tier, "replica": rid,
                                "score": round(
                                    self._breaker.score(machine), 3),
                                "autoscaler": self.autoscaler_id})
        if was_draining:
            # it was being removed anyway — reap, don't replace
            self.publish_servefault(force=True)
            self.publish_telemetry(force=True)
            return
        if self._breaker.is_quarantined(machine):
            with self._lock:
                self._stats["replacements_blocked"] += 1
                self._stats["last_reason"][tier] = (
                    f"replacement blocked: breaker open for {machine} "
                    f"({self._breaker.score(machine):.1f} deaths in "
                    f"window)")
            self.publish_servefault(force=True)
            return
        self._replace(tier, rid)

    def _replace(self, tier: str, dead_rid: str) -> None:
        """Spawn a 1-for-1 replacement through the tier factory —
        OUTSIDE the hysteresis/cooldown machinery (death is not load;
        the tier must return to strength now, not after up_delay_s)."""
        from .disagg import servefault_metrics

        try:
            replica = self.specs[tier].factory()
        except Exception as e:  # noqa: BLE001 — no capacity right now
            with self._lock:
                self._stats["last_reason"][tier] = (
                    f"replacement blocked: {type(e).__name__}: {e}")
            self.publish_servefault(force=True)
            return
        rid = (self.router.add_prefill(replica) if tier == "prefill"
               else self.router.add_decode(replica))
        self._refresh_managed()
        with self._lock:
            self._stats["replacements"][tier] += 1
        servefault_metrics()["replacements"].inc(tags={"tier": tier})
        ev = {"kind": "replace", "tier": tier, "replica": rid,
              "for": dead_rid, "autoscaler": self.autoscaler_id}
        _notify_event(ev)
        _notify_resilience(dict(ev))
        self.publish_servefault(force=True)
        self.publish_telemetry(force=True)

    def servefault_stats(self) -> Dict[str, Any]:
        """The self-healer's contribution to the servefault surface."""
        with self._lock:
            sf: Dict[str, Any] = {
                "deaths": dict(self._stats["deaths"]),
                "replacements": dict(self._stats["replacements"]),
                "replacements_blocked":
                    self._stats["replacements_blocked"],
                "breaker_trips": self._stats["breaker_trips"],
                "drains_reaped": self._stats["drains_reaped"],
            }
        sf.update(role="healer", autoscaler_id=self.autoscaler_id,
                  router=self.router.router_id,
                  breaker_open=self._breaker.excluded(),
                  breaker_threshold=self._breaker.threshold,
                  watching=self._watching)
        return sf

    def publish_servefault(self, force: bool = False) -> None:
        from .disagg import _push_servefault

        now = time.monotonic()
        if not force and now - self._last_sf_push < 0.5:
            return
        self._last_sf_push = now
        _push_servefault(self.autoscaler_id, self.servefault_stats())

    # ------------------------------------------------------------ status

    def status(self) -> Dict[str, Any]:
        with self._lock:
            s = {
                "autoscaler_id": self.autoscaler_id,
                "router": self.router.router_id,
                "target_p99_ms": self.policy.target_p99_ms,
                "interval_s": self.interval_s,
                "drain_grace_s": self.drain_grace_s,
                "scale_ups": dict(self._stats["scale_ups"]),
                "scale_downs": dict(self._stats["scale_downs"]),
                "drains_completed": self._stats["drains_completed"],
                "drains_forced": self._stats["drains_forced"],
                "drains_reaped": self._stats["drains_reaped"],
                "deaths": dict(self._stats["deaths"]),
                "replacements": dict(self._stats["replacements"]),
                "replacements_blocked":
                    self._stats["replacements_blocked"],
                "breaker_trips": self._stats["breaker_trips"],
                "replica_seconds": {
                    t: round(v, 3) for t, v
                    in self._stats["replica_seconds"].items()},
                "wakeups": dict(self._stats["wakeups"]),
                "last_reason": dict(self._stats["last_reason"]),
                "draining": [{"tier": d.tier, "rid": d.rid}
                             for d in self._draining],
            }
        s["breaker_open"] = self._breaker.excluded()
        s["watching"] = self._watching
        for tier in TIERS:
            reps = self.router.tier_replicas(tier)
            s[f"{tier}_replicas"] = len(reps)
            s[f"{tier}_active"] = sum(1 for r in reps
                                      if not r["draining"])
            s[f"{tier}_bounds"] = [self.specs[tier].policy.min_replicas,
                                   self.specs[tier].policy.max_replicas]
        return s

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        w = _worker()
        if w is None:
            return
        try:
            w.conductor.notify("report_autoscale_stats", w.worker_id,
                               self.autoscaler_id, self.status())
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass

    # -------------------------------------------------------------- loop

    def start(self) -> "DisaggAutoscaler":
        self.watch()  # self-healing is event-driven, not tick-driven

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception:  # noqa: BLE001 — keep the loop alive
                    import traceback

                    traceback.print_exc()

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="serve-autoscale")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self.unwatch()
        for t in self._heals:
            t.join(timeout=30.0)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        # finalize in-progress drains NOW: an abandoned draining
        # replica would stay registered (and its engine running)
        # forever — the replica-side grace still runs in the teardown
        # threads, which we wait out below
        if self._draining:
            past_every_deadline = max(
                [time.monotonic()]
                + [d.grace_deadline for d in self._draining])
            self._advance_drains(past_every_deadline, [])
        for t in self._teardowns:
            t.join(timeout=self.drain_grace_s + 15.0)
        self.publish_telemetry(force=True)
        self.publish_servefault(force=True)


__all__ = ["DisaggAutoscaler", "DisaggPolicy", "ScalingPolicy",
           "SlidingWindow", "TierSpec", "autoscale_metrics",
           "default_target_p99_ms"]
