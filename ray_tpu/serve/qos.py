"""QoS policy for the HTTP front door (serve/gateway.py).

Three concerns, all decided BEFORE a request holds any decode
resources:

- **API-key -> tenant resolution.** The gateway's `Authorization:
  Bearer <key>` header maps onto the multi-tenant LoRA tenant id
  (serve/lora.py); the tenant then flows through the router's
  per-tenant accounting, adapter affinity, and namespace-keyed KV
  exactly as an in-process ``generate(tenant=...)`` call would.

- **Per-tenant token-bucket rate limits and quotas.** A classic
  refill-at-`rate_rps` bucket bounds sustained request rate (burst
  absorbs spikes); `max_inflight` bounds concurrency; `max_requests`
  is a lifetime quota fed by the SAME per-tenant accounting the
  router keeps (``DisaggRouter.tenant_stats()`` dispatched counts),
  so a tenant cannot reset its quota by reconnecting through a fresh
  gateway replica. Every rejection raises the serving plane's one
  shed type — :class:`RequestShedError` with cause ``rate_limit`` or
  ``quota`` — which the gateway maps to HTTP 429 + ``Retry-After``.

- **Priority classes.** Two classes: ``interactive`` (latency-bound;
  may preempt a batch-tier decode slot through the router's
  cancel + replay-with-history machinery) and ``batch`` (throughput
  traffic; preemptible, absorbs sheds under pressure). A request
  names its class (``priority`` body field / ``X-Priority`` header);
  the tenant's policy supplies the default.

This module also hosts the gateway telemetry helpers — the lazy
Prometheus family and the conductor push fns — so serve/disagg.py can
count preemptions into the SAME gateway surface without importing the
gateway (qos imports only serve/handle.py; no cycle).
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

from .handle import RequestShedError

INTERACTIVE = "interactive"
BATCH = "batch"
CLASSES = (INTERACTIVE, BATCH)


def shed_outcome(e: RequestShedError) -> tuple:
    """Map a shed's cause onto the flight recorder's outcome
    vocabulary (observability/requests.py): deadline, disconnect and
    preempt each get their own tail-retention class; everything else
    (rate_limit / quota / capacity / failover) is a plain ``shed``.
    ONE mapping shared by the gateway and the router so the same shed
    never lands under two outcome names on different surfaces."""
    cause = getattr(e, "cause", None)
    outcome = {"deadline": "deadline",
               "disconnect": "disconnect",
               "preempt": "preempt",
               "preempted": "preempt"}.get(cause, "shed")
    return outcome, cause

# ------------------------------------------------------------- telemetry

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def gateway_metrics() -> Dict[str, Any]:
    """Lazily-constructed gateway metric family (util.metrics
    exposition). Built on first use — importing this module must not
    register metrics."""
    global _metrics
    if _metrics is not None:
        return _metrics
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Histogram

            m = {
                "requests": Counter(
                    "ray_tpu_gateway_requests_total",
                    "HTTP requests by route, priority class, and "
                    "status code",
                    tag_keys=("route", "class", "code")),
                "ttft_ms": Histogram(
                    "ray_tpu_gateway_ttft_ms",
                    "ms from accept to first byte written, by class",
                    boundaries=[1, 5, 10, 25, 50, 100, 250, 500,
                                1000, 2500, 5000, 10000],
                    tag_keys=("class",)),
                "rate_limited": Counter(
                    "ray_tpu_gateway_rate_limited_total",
                    "requests rejected by the QoS gate, by tenant",
                    tag_keys=("tenant",)),
                "preemptions": Counter(
                    "ray_tpu_gateway_preemptions_total",
                    "batch-tier decode slots preempted by "
                    "interactive requests"),
            }
            # rebind ONCE, fully constructed — a reader never sees a
            # half-built dict
            _metrics = m
    return _metrics


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def push_gateway_stats(component_id: str, stats: Dict[str, Any]) -> None:
    """Best-effort snapshot push to the conductor's gateway roster
    (feeds util.state.gateway_status(), `ray_tpu gateway`, and
    /api/gateway with one set of numbers)."""
    try:
        w = _worker()
        if w is None:
            return
        w.conductor.notify("report_gateway_stats", w.worker_id,
                           str(component_id), stats)
    except Exception:  # noqa: BLE001 — telemetry only
        pass


def push_gateway_event(event: Dict[str, Any]) -> None:
    """Best-effort instant marker (accept / first_byte / preempt /
    rate_limit / disconnect) for the merged timeline's gateway lane."""
    try:
        w = _worker()
        if w is None:
            return
        w.conductor.notify("report_gateway_event", dict(event))
    except Exception:  # noqa: BLE001 — telemetry only
        pass


# ------------------------------------------------------------ the gate

class TokenBucket:
    """Refill-at-`rate_rps` token bucket with `burst` capacity.

    ``try_acquire`` returns 0.0 on success (one token consumed) or the
    seconds until a token WILL exist — the Retry-After the caller
    should surface. Time is injectable for tests."""

    def __init__(self, rate_rps: float, burst: Optional[float] = None):
        self.rate_rps = float(rate_rps)
        self.burst = float(burst if burst is not None
                           else max(1.0, self.rate_rps))
        self._tokens = self.burst
        self._stamp: Optional[float] = None
        self._lock = threading.Lock()

    def try_acquire(self, cost: float = 1.0,
                    now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self._stamp is not None and self.rate_rps > 0:
                self._tokens = min(
                    self.burst,
                    self._tokens + (now - self._stamp) * self.rate_rps)
            self._stamp = now
            if self._tokens >= cost:
                self._tokens -= cost
                return 0.0
            if self.rate_rps <= 0:
                return 60.0  # zero-rate tenant: effectively blocked
            return (cost - self._tokens) / self.rate_rps


@dataclass
class TenantPolicy:
    """One tenant's front-door contract. ``None`` fields are
    unlimited; ``priority`` is the DEFAULT class when the request
    names none."""

    rate_rps: Optional[float] = None
    burst: Optional[float] = None
    max_inflight: Optional[int] = None
    max_requests: Optional[int] = None
    priority: str = INTERACTIVE

    def __post_init__(self):
        if self.priority not in CLASSES:
            raise ValueError(
                f"unknown priority class {self.priority!r}; "
                f"expected one of {CLASSES}")


_ANON = "_anonymous"


class QosGate:
    """Admission policy evaluated by the gateway before a request
    touches the router: resolve the tenant, check its bucket/quota,
    pick its class. Thread-safe; one gate is shared by every handler
    coroutine (and by N gateway replicas when they share a process).

    ``router`` (optional, a DisaggRouter) feeds the lifetime quota
    from the router's own per-tenant dispatched counter, so the quota
    survives gateway restarts — the accounting and the enforcement
    read one set of numbers."""

    def __init__(self,
                 api_keys: Optional[Dict[str, str]] = None,
                 policies: Optional[Dict[str, TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None,
                 router: Any = None):
        self._api_keys = dict(api_keys or {})
        self._policies = dict(policies or {})
        self._default = default_policy or TenantPolicy()
        self._router = router
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._inflight: Dict[str, int] = {}
        self._admitted: Dict[str, int] = {}
        self._rejected: Dict[str, Dict[str, int]] = {}
        self._stats = {"admitted": 0, "rate_limited": 0,
                       "quota_exceeded": 0}

    # ------------------------------------------------------- resolution

    def resolve(self, api_key: Optional[str] = None,
                tenant: Optional[str] = None) -> Optional[str]:
        """API-key -> tenant. With a key table configured, an unknown
        key is a hard authentication failure (the gateway's 401); with
        no table, the explicit tenant hint (X-Tenant header / OpenAI
        ``user`` field) passes through."""
        if api_key:
            mapped = self._api_keys.get(api_key)
            if mapped is not None:
                return mapped
            if self._api_keys:
                raise PermissionError("unknown API key")
        return tenant

    def policy(self, tenant: Optional[str]) -> TenantPolicy:
        if tenant is not None and tenant in self._policies:
            return self._policies[tenant]
        return self._default

    def classify(self, tenant: Optional[str],
                 requested: Optional[str] = None) -> str:
        """The request's priority class: the request's own ask when
        valid, else the tenant policy's default. An unknown ask raises
        ValueError (the gateway's 400)."""
        if requested:
            if requested not in CLASSES:
                raise ValueError(
                    f"unknown priority class {requested!r}; expected "
                    f"one of {CLASSES}")
            return requested
        return self.policy(tenant).priority

    # -------------------------------------------------------- admission

    def _key(self, tenant: Optional[str]) -> str:
        return tenant if tenant is not None else _ANON

    def admit(self, tenant: Optional[str],
              cls: str = INTERACTIVE) -> None:
        """Charge one request against the tenant's bucket and quotas;
        raises :class:`RequestShedError` (cause ``rate_limit`` |
        ``quota``) on rejection. A successful admit must be paired
        with :meth:`release`."""
        pol = self.policy(tenant)
        key = self._key(tenant)
        router_used = 0
        if pol.max_requests is not None and self._router is not None \
                and tenant is not None:
            try:
                router_used = int(self._router.tenant_stats()
                                  .get(tenant, {})
                                  .get("dispatched", 0))
            except Exception:  # noqa: BLE001 — accounting is advisory
                router_used = 0
        cause = None
        retry_after = 1.0
        with self._lock:
            if pol.max_requests is not None and \
                    max(self._admitted.get(key, 0),
                        router_used) >= pol.max_requests:
                cause = "quota"
                msg = (f"tenant {key!r}: lifetime request quota "
                       f"{pol.max_requests} exhausted")
                self._stats["quota_exceeded"] += 1
            elif pol.max_inflight is not None and \
                    self._inflight.get(key, 0) >= pol.max_inflight:
                cause = "quota"
                msg = (f"tenant {key!r}: max_inflight "
                       f"{pol.max_inflight} reached")
                self._stats["quota_exceeded"] += 1
            elif pol.rate_rps is not None:
                bucket = self._buckets.get(key)
                if bucket is None:
                    bucket = TokenBucket(pol.rate_rps, pol.burst)
                    self._buckets[key] = bucket
                wait = bucket.try_acquire()
                if wait > 0:
                    cause = "rate_limit"
                    retry_after = max(wait, 0.05)
                    msg = (f"tenant {key!r}: rate limit "
                           f"{pol.rate_rps:g} req/s exceeded")
                    self._stats["rate_limited"] += 1
            if cause is None:
                self._inflight[key] = self._inflight.get(key, 0) + 1
                self._admitted[key] = self._admitted.get(key, 0) + 1
                self._stats["admitted"] += 1
                return
            rej = self._rejected.setdefault(key, {})
            rej[cause] = rej.get(cause, 0) + 1
        # rejection side effects OUTSIDE the lock — overload must not
        # serialize healthy admissions behind a socket write
        gateway_metrics()["rate_limited"].inc(tags={"tenant": key})
        push_gateway_event({"kind": "rate_limit", "tenant": key,
                            "cause": cause, "class": cls,
                            "retry_after_s": round(retry_after, 3)})
        raise RequestShedError(msg, retry_after_s=retry_after,
                               cause=cause)

    def release(self, tenant: Optional[str]) -> None:
        key = self._key(tenant)
        with self._lock:
            n = self._inflight.get(key, 0)
            if n > 0:
                self._inflight[key] = n - 1

    # ---------------------------------------------------------- surface

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            tenants = {}
            for key in (set(self._admitted) | set(self._inflight)
                        | set(self._rejected)):
                tenants[key] = {
                    "admitted": self._admitted.get(key, 0),
                    "inflight": self._inflight.get(key, 0),
                    "rejected": dict(self._rejected.get(key, {})),
                }
            return dict(self._stats, tenants=tenants)


__all__ = ["BATCH", "CLASSES", "INTERACTIVE", "QosGate", "TenantPolicy",
           "TokenBucket", "gateway_metrics", "push_gateway_event",
           "push_gateway_stats", "shed_outcome"]
