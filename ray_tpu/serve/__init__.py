"""ray_tpu.serve — model-serving library, analog of the reference's
python/ray/serve (api.py: serve.run :543, @serve.deployment; _private/
api.py:208 serve_start; _private/client.py:243 deploy_application).

Architecture (SURVEY.md §3.5): a singleton ServeController actor reconciles
deployment targets into ReplicaActors and runs an HTTP ProxyActor; handles
route requests pow-2 over replica queue lengths. TPU-first notes: replicas
pin jitted model shards, @serve.batch keeps the MXU fed with batched forward
passes, @serve.multiplexed LRU-loads weight sets into HBM."""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Union

import cloudpickle

from .asgi import ingress  # noqa: F401
from .autoscale import (DisaggAutoscaler, DisaggPolicy,  # noqa: F401
                        ScalingPolicy, SlidingWindow, TierSpec)
from .batching import batch  # noqa: F401
from .config import AutoscalingConfig, DeploymentConfig, HTTPOptions  # noqa: F401
from .context import get_request_context  # noqa: F401
from .controller import ServeController
from .disagg import (DecodeServer, DisaggRouter,  # noqa: F401
                     PrefillServer, ReplicaDeadError)
from .gateway import GatewayServer  # noqa: F401
from .handle import (CONTROLLER_NAME, DeploymentHandle,  # noqa: F401
                     DeploymentResponse, RequestShedError)
from .http_util import Request, Response  # noqa: F401
from .multiplex import (get_multiplexed_model_id, multiplexed,  # noqa: F401
                        request_tenant)
from .qos import (BATCH, INTERACTIVE, QosGate,  # noqa: F401
                  TenantPolicy, TokenBucket)
from .replica import HandleMarker


class Application:
    """A deployment bound to init args — reference serve/_private/
    deployment_graph_build.py's DeploymentNode, minus the DAG generality Serve
    dropped upstream too."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self._deployment = deployment
        self._args = args
        self._kwargs = kwargs


class Deployment:
    """Created by @serve.deployment — reference python/ray/serve/
    deployment.py."""

    def __init__(self, func_or_class, name: str,
                 config: Optional[DeploymentConfig] = None):
        self._func_or_class = func_or_class
        self.name = name
        self.config = config or DeploymentConfig()

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[Union[int, str]] = None,
                max_ongoing_requests: Optional[int] = None,
                max_queued_requests: Optional[int] = None,
                user_config: Optional[Any] = None,
                autoscaling_config: Optional[Union[dict, AutoscalingConfig]] = None,
                health_check_period_s: Optional[float] = None,
                health_check_timeout_s: Optional[float] = None,
                graceful_shutdown_timeout_s: Optional[float] = None,
                ray_actor_options: Optional[Dict[str, Any]] = None
                ) -> "Deployment":
        import dataclasses
        cfg = dataclasses.replace(self.config)
        if isinstance(autoscaling_config, dict):
            autoscaling_config = AutoscalingConfig(**autoscaling_config)
        if num_replicas == "auto":
            autoscaling_config = autoscaling_config or AutoscalingConfig(
                min_replicas=1, max_replicas=8)
            num_replicas = None
        for field, value in [("num_replicas", num_replicas),
                             ("max_ongoing_requests", max_ongoing_requests),
                             ("max_queued_requests", max_queued_requests),
                             ("user_config", user_config),
                             ("autoscaling_config", autoscaling_config),
                             ("health_check_period_s", health_check_period_s),
                             ("health_check_timeout_s", health_check_timeout_s),
                             ("graceful_shutdown_timeout_s",
                              graceful_shutdown_timeout_s),
                             ("ray_actor_options", ray_actor_options)]:
            if value is not None:
                setattr(cfg, field, value)
        return Deployment(self._func_or_class, name or self.name, cfg)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"deployment {self.name} cannot be called directly; use "
            f".bind() + serve.run(), then handle.remote(...)")


def deployment(_func_or_class=None, *, name: Optional[str] = None, **options):
    """@serve.deployment — reference serve/api.py deployment decorator."""

    def deco(fc):
        d = Deployment(fc, name or fc.__name__)
        if options:
            d = d.options(**options)
        return d

    if _func_or_class is not None:
        return deco(_func_or_class)
    return deco


# -- controller lifecycle ---------------------------------------------------

def _get_controller(create: bool = True, http_options:
                    Optional[HTTPOptions] = None):
    import ray_tpu
    if not ray_tpu.is_initialized():
        ray_tpu.init(ignore_reinit_error=True)
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:  # noqa: BLE001 — not started yet
        if not create:
            raise RuntimeError("Serve is not running on this cluster")
    http_options = http_options or HTTPOptions()
    ctrl = ray_tpu.remote(ServeController).options(
        name=CONTROLLER_NAME, max_concurrency=64).remote(
            http_options.host, http_options.port, http_options.grpc_port,
            http_options.proxy_location)
    return ctrl


def start(http_options: Optional[HTTPOptions] = None,
          **http_kwargs) -> None:
    """Start the Serve control plane — reference serve/_private/api.py:208."""
    if http_options is None and http_kwargs:
        http_options = HTTPOptions(**http_kwargs)
    _get_controller(create=True, http_options=http_options)


def _build_app_config(target: Union[Application, Deployment], name: str,
                      route_prefix: str) -> Dict[str, Any]:
    if isinstance(target, Deployment):
        target = target.bind()
    seen: Dict[str, Dict[str, Any]] = {}

    def visit(app: Application) -> str:
        dep = app._deployment

        def swap(obj):
            if isinstance(obj, Application):
                return HandleMarker(visit(obj))
            if isinstance(obj, (list, tuple)):
                return type(obj)(swap(x) for x in obj)
            if isinstance(obj, dict):
                return {k: swap(v) for k, v in obj.items()}
            return obj

        args = tuple(swap(a) for a in app._args)
        kwargs = {k: swap(v) for k, v in app._kwargs.items()}
        if dep.name not in seen:
            seen[dep.name] = {
                "name": dep.name,
                "serialized_callable": cloudpickle.dumps(dep._func_or_class),
                "init_args": cloudpickle.dumps((args, kwargs)),
                "config": dep.config,
            }
        return dep.name

    ingress = visit(target)
    return {"name": name, "route_prefix": route_prefix, "ingress": ingress,
            "deployments": list(seen.values())}


def run(target: Union[Application, Deployment], *, name: str = "default",
        route_prefix: str = "/", blocking_timeout_s: float = 120.0,
        _blocking: bool = True) -> DeploymentHandle:
    """Deploy an application and wait for it to be RUNNING — reference
    serve/api.py:543."""
    import ray_tpu
    ctrl = _get_controller(create=True)
    cfg = _build_app_config(target, name, route_prefix)
    ray_tpu.get(ctrl.deploy_application.remote(cfg), timeout=60.0)
    if _blocking:
        deadline = time.monotonic() + blocking_timeout_s
        while time.monotonic() < deadline:
            st = ray_tpu.get(ctrl.get_serve_status.remote(), timeout=30.0)
            app = st["applications"].get(name)
            if app is not None and app["status"] == "RUNNING":
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(
                f"application '{name}' did not become RUNNING within "
                f"{blocking_timeout_s}s")
    return DeploymentHandle(cfg["ingress"], name)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    import ray_tpu
    ctrl = _get_controller(create=False)
    st = ray_tpu.get(ctrl.get_serve_status.remote(), timeout=30.0)
    app = st["applications"].get(name)
    if app is None:
        raise ValueError(f"no application named '{name}'")
    return DeploymentHandle(app["ingress"], name)


def get_deployment_handle(deployment_name: str, app_name: str = "default"
                          ) -> DeploymentHandle:
    return DeploymentHandle(deployment_name, app_name)


def status() -> Dict[str, Any]:
    import ray_tpu
    ctrl = _get_controller(create=False)
    return ray_tpu.get(ctrl.get_serve_status.remote(), timeout=30.0)


def proxy_address() -> Optional[tuple]:
    import ray_tpu
    ctrl = _get_controller(create=False)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        addr = ray_tpu.get(ctrl.get_proxy_address.remote(), timeout=30.0)
        if addr is not None:
            return tuple(addr)
        time.sleep(0.1)
    return None


def grpc_address() -> Optional[tuple]:
    """(host, port) of the gRPC ingress, or None when it is disabled
    (enable with serve.start(grpc_port=...))."""
    import ray_tpu
    ctrl = _get_controller(create=False)
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline:
        state, addr = ray_tpu.get(ctrl.get_grpc_address.remote(),
                                  timeout=30.0)
        if state == "disabled":
            return None
        if addr is not None:
            return tuple(addr)
        time.sleep(0.1)
    return None


def grpc_call(address: tuple, *args, application: str = "default",
              call_method: str = "__call__", streaming: bool = False,
              timeout_s: float = 60.0, **kwargs):
    """Client helper for the generic gRPC ingress: returns the result of
    the app's ingress deployment, or an iterator of chunks when
    streaming=True (reference: serve gRPC client usage via generated
    stubs; here messages are cloudpickled so no stub generation step)."""
    import cloudpickle as cp
    import grpc

    channel = grpc.insecure_channel(f"{address[0]}:{address[1]}")
    md = (("application", application), ("call_method", call_method))
    payload = cp.dumps((args, kwargs))
    if not streaming:
        fn = channel.unary_unary("/ray_tpu.serve.Ingress/Call")
        try:
            return cp.loads(fn(payload, metadata=md, timeout=timeout_s))
        finally:
            channel.close()
    fn = channel.unary_stream("/ray_tpu.serve.Ingress/CallStreaming")

    def it():
        try:
            for msg in fn(payload, metadata=md, timeout=timeout_s):
                yield cp.loads(msg)
        finally:
            channel.close()

    return it()


def delete(name: str) -> None:
    import ray_tpu
    from .handle import _drop_routers
    ctrl = _get_controller(create=False)
    ray_tpu.get(ctrl.delete_application.remote(name), timeout=60.0)
    _drop_routers(name)


def shutdown() -> None:
    """Tear down all of Serve — reference serve/api.py serve.shutdown."""
    import ray_tpu
    from .handle import _drop_routers
    _drop_routers()
    try:
        ctrl = _get_controller(create=False)
    except RuntimeError:
        return
    try:
        ray_tpu.get(ctrl.graceful_shutdown.remote(), timeout=30.0)
    except Exception:  # noqa: BLE001 — force-kill below
        pass
    try:
        ray_tpu.kill(ctrl)
    except Exception:  # noqa: BLE001
        pass
