"""Dynamic request batching — analog of the reference's
python/ray/serve/batching.py (@serve.batch).

A decorated method receives a *list* of inputs; callers enqueue single inputs
and a background flusher invokes the underlying function once per batch
(whichever of max_batch_size / batch_wait_timeout_s is hit first). On TPU
this is the step that keeps the MXU fed: replicas batch requests into one
jitted forward pass instead of one compile-sized call per request."""
from __future__ import annotations

import functools
import queue
import threading
import weakref
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional

# Batch-occupancy telemetry: on TPU the whole point of @serve.batch is
# keeping the MXU fed, so the flushed batch size (and its fraction of
# max_batch_size) is the gauge that says whether it is working. One set
# of metric objects per process; batchers are distinguished by the "fn"
# label.
_metrics_cache: Dict[str, Any] = {}
_metrics_lock = threading.Lock()


def _batch_metrics() -> Dict[str, Any]:
    # double-checked init: unlocked fast path per flush; the lock only
    # guards first-time registration so concurrent batcher flush threads
    # cannot register duplicate metric objects
    if _metrics_cache:
        return _metrics_cache
    with _metrics_lock:
        if not _metrics_cache:
            _build_metrics()
    return _metrics_cache


def _build_metrics() -> None:
    from ray_tpu.util.metrics import Gauge, Histogram

    _metrics_cache.update(
        size=Histogram(
            "serve_batch_size", "flushed batch sizes",
            boundaries=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0],
            tag_keys=("fn",)),
        occupancy=Gauge(
            "serve_batch_occupancy",
            "last flushed batch size / max_batch_size",
            tag_keys=("fn",)),
        queue_depth=Gauge(
            "serve_batch_queue_depth",
            "items waiting in the batcher queue",
            tag_keys=("fn",)))


class PerInstance:
    """Lazily builds one state object per bound instance (weakly held), so a
    decorated *class* doesn't share one batcher/cache across instances —
    the reference attaches these to self lazily for the same reason
    (serve/batching.py _get_or_create_batch_queue)."""

    def __init__(self, factory: Callable[[], Any]):
        self._factory = factory
        self._lock = threading.Lock()
        self._by_instance: "weakref.WeakKeyDictionary" = \
            weakref.WeakKeyDictionary()
        self._default: Optional[Any] = None

    def get(self, self_arg: Optional[Any]) -> Any:
        with self._lock:
            if self_arg is None:
                if self._default is None:
                    self._default = self._factory()
                return self._default
            obj = self._by_instance.get(self_arg)
            if obj is None:
                obj = self._factory()
                self._by_instance[self_arg] = obj
            return obj

    def __reduce__(self):
        # Locks/weakrefs are per-process; rebuild empty in the replica.
        return (PerInstance, (self._factory,))


class _BatchQueue:
    def __init__(self, fn: Callable[..., List[Any]], max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._timeout = batch_wait_timeout_s
        self._queue: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def __reduce__(self):
        # Queues/locks/threads are per-process state — rebuild fresh in the
        # replica rather than pickling them with the deployment class.
        return (_BatchQueue,
                (self._fn, self._max_batch_size, self._timeout))

    def _ensure_thread(self):
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._flush_loop, daemon=True,
                    name="serve-batcher")
                self._thread.start()

    def submit(self, self_arg, item) -> Future:
        fut: Future = Future()
        self._queue.put((self_arg, item, fut))
        self._ensure_thread()
        return fut

    def _flush_loop(self):
        while True:
            batch = [self._queue.get()]  # block for the first item
            try:
                while len(batch) < self._max_batch_size:
                    batch.append(self._queue.get(timeout=self._timeout))
            except queue.Empty:
                pass
            self._run_batch(batch)

    def _run_batch(self, batch):
        self_arg = batch[0][0]
        items = [b[1] for b in batch]
        futs = [b[2] for b in batch]
        try:
            m = _batch_metrics()
            tags = {"fn": getattr(self._fn, "__name__", "batch")}
            m["size"].observe(float(len(items)), tags=tags)
            m["occupancy"].set(len(items) / max(1, self._max_batch_size),
                               tags=tags)
            m["queue_depth"].set(self._queue.qsize(), tags=tags)
        except Exception:  # noqa: BLE001 — telemetry must not fail a batch
            pass
        try:
            if self_arg is not None:
                results = self._fn(self_arg, items)
            else:
                results = self._fn(items)
            if not isinstance(results, list) or len(results) != len(items):
                raise TypeError(
                    f"@serve.batch function must return a list of "
                    f"{len(items)} results, got {type(results).__name__}")
            for f, r in zip(futs, results):
                f.set_result(r)
        except Exception as e:  # noqa: BLE001 — fan the error out to callers
            for f in futs:
                if not f.done():
                    f.set_exception(e)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 10,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: turn a method taking List[T] -> List[R] into one taking a
    single T (returns R), with dynamic batching across concurrent callers.
    Reference python/ray/serve/batching.py:@serve.batch."""

    def deco(fn: Callable) -> Callable:
        queues = PerInstance(
            lambda: _BatchQueue(fn, max_batch_size, batch_wait_timeout_s))

        @functools.wraps(fn)
        def wrapper(*args):
            # Method (self, item) or free function (item).
            if len(args) == 2:
                self_arg, item = args
            elif len(args) == 1:
                self_arg, item = None, args[0]
            else:
                raise TypeError("@serve.batch functions take one argument")
            return queues.get(self_arg).submit(self_arg, item).result()

        wrapper._serve_batch_queues = queues  # introspection/tests
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
