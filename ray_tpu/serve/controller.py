"""ServeController — analog of the reference's python/ray/serve/_private/
controller.py:85 (ServeController) + deployment_state.py:1225,2447
(DeploymentState/DeploymentStateManager reconciliation) +
autoscaling_policy.py (queue-length autoscaling) + long_poll.py (config push
modeled as a version counter routers poll).

One named actor owns all Serve state; a background thread reconciles target
vs running replicas, health-checks them, and autoscales."""
from __future__ import annotations

import math
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .config import AutoscalingConfig, DeploymentConfig
from .handle import CONTROLLER_NAME  # noqa: F401 — canonical name lives here

PROXY_NAME = "SERVE_PROXY"


class _DeploymentState:
    def __init__(self, app_name: str, name: str, serialized_callable: bytes,
                 init_args: bytes, config: DeploymentConfig):
        self.app_name = app_name
        self.name = name
        self.serialized_callable = serialized_callable
        self.init_args = init_args
        self.config = config
        self.target_num_replicas = config.num_replicas
        self.scaling_policy = None  # autoscale.ScalingPolicy, lazy
        if config.autoscaling_config is not None:
            from .autoscale import ScalingPolicy

            cfg = config.autoscaling_config
            self.target_num_replicas = max(cfg.min_replicas, 1)
            # the SHARED hysteresis engine (serve/autoscale.py) — the
            # same persistence gates the disagg tier loop uses;
            # cooldown 0 keeps the reference controller semantics
            self.scaling_policy = ScalingPolicy(
                cfg.min_replicas, cfg.max_replicas,
                up_delay_s=cfg.upscale_delay_s,
                down_delay_s=cfg.downscale_delay_s, cooldown_s=0.0)
        self.replicas: List[Tuple[str, Any]] = []  # (tag, ActorHandle)
        self.last_health_check = 0.0
        self.status = "DEPLOYING"
        # handle_id -> (total inflight from that handle, monotonic ts)
        self.handle_metrics: Dict[str, Tuple[float, float]] = {}
        # replica_tag -> last get_metrics() snapshot (collected on the
        # health-check cadence; feeds `serve status` and /api/serve)
        self.replica_metrics: Dict[str, Dict[str, Any]] = {}

    def to_status(self) -> Dict[str, Any]:
        mets = list(self.replica_metrics.values())
        # worst-replica recent p99s (merging percentiles across windows
        # would be a lie; the max is the honest deployment-level number
        # beside the cumulative counters)
        recent = {}
        for key in ("ttft_ms", "latency_ms"):
            vals = [m["recent"][key]["p99"] for m in mets
                    if (m.get("recent") or {}).get(key, {}).get("n")]
            if vals:
                recent[f"{key[:-3]}_p99_ms"] = round(max(vals), 3)
        return {"name": self.name, "status": self.status,
                "target_num_replicas": self.target_num_replicas,
                "replicas": [tag for tag, _ in self.replicas],
                "metrics": {
                    "inflight": sum(m.get("inflight", 0) for m in mets),
                    "num_requests": sum(m.get("num_requests", 0)
                                        for m in mets),
                    "num_errors": sum(m.get("num_errors", 0)
                                      for m in mets),
                    "recent": recent,
                    "per_replica": dict(self.replica_metrics)}}


class ServeController:
    """Reference controller.py:85 — singleton detached actor."""

    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 8000,
                 grpc_port: Optional[int] = None,
                 proxy_location: str = "EveryNode"):
        self._apps: Dict[str, Dict[str, Any]] = {}
        self._deployments: Dict[Tuple[str, str], _DeploymentState] = {}
        self._version = 0
        self._lock = threading.RLock()
        self._shutting_down = False
        self._http_host = http_host
        self._http_port = http_port
        self._grpc_port = grpc_port
        self._grpc_addr: Optional[Tuple[str, int]] = None
        self._proxy = None  # the head-node proxy (primary address)
        self._proxy_addr: Optional[Tuple[str, int]] = None
        self._proxy_location = proxy_location
        # per-node proxy fleet (reference proxy_state.py ProxyStateManager:
        # one ProxyActor per alive node, reconciled with cluster topology)
        self._proxies: Dict[str, Any] = {}
        self._proxy_addrs: Dict[str, Tuple[str, int]] = {}
        self._proxy_pending: set = set()
        self._last_topology_check = 0.0
        self._reconcile_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile")
        self._reconcile_thread.start()

    # -- deploy / delete ----------------------------------------------------
    def deploy_application(self, app_config: Dict[str, Any]) -> None:
        """app_config: {name, route_prefix, ingress,
        deployments: [{name, serialized_callable, init_args, config}]}."""
        app = app_config["name"]
        # Tear down any previous version OUTSIDE the lock: replica drain can
        # take graceful_shutdown_timeout_s per replica and must not block
        # get_serve_status/poll_update/record_handle_metrics.
        self.delete_application(app)
        with self._lock:
            self._apps[app] = {
                "route_prefix": app_config.get("route_prefix", "/"),
                "ingress": app_config["ingress"],
                "deployments": [d["name"]
                                for d in app_config["deployments"]],
            }
            for d in app_config["deployments"]:
                cfg = d["config"]
                cfg.validate()
                self._deployments[(app, d["name"])] = _DeploymentState(
                    app, d["name"], d["serialized_callable"], d["init_args"],
                    cfg)
            self._version += 1

    def delete_application(self, app: str) -> None:
        with self._lock:
            if app not in self._apps:
                return
            doomed = [k for k in self._deployments if k[0] == app]
            states = [self._deployments.pop(k) for k in doomed]
            del self._apps[app]
            self._version += 1
        for st in states:
            for tag, handle in st.replicas:
                self._stop_replica(handle, st.config)

    def graceful_shutdown(self) -> None:
        with self._lock:
            self._shutting_down = True
            states = list(self._deployments.values())
            self._deployments.clear()
            self._apps.clear()
            self._version += 1
        import ray_tpu
        for st in states:
            for tag, handle in st.replicas:
                self._stop_replica(handle, st.config)
        with self._lock:
            doomed_proxies = list(self._proxies.values())
            self._proxies.clear()
            self._proxy_addrs.clear()
            self._proxy = None
        # join the reconcile thread BEFORE clearing the KV mirror: an
        # in-flight _publish_status must not re-publish ghost status
        # after the delete (nothing would ever overwrite it again)
        self._reconcile_thread.join(timeout=10.0)
        try:
            from ray_tpu._private import worker as worker_mod

            worker_mod.global_worker.conductor.notify(
                "kv_del", "serve:status", "serve")
        except Exception:  # noqa: BLE001 — conductor may be gone too
            pass
        for actor in doomed_proxies:
            try:
                ray_tpu.get(actor.graceful_shutdown.remote(), timeout=5.0)
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 — proxy may already be gone
                pass

    # -- introspection (state API / routers / proxy) ------------------------
    def get_replicas(self, app: str, deployment: str
                     ) -> Tuple[int, List[Tuple[str, Any]]]:
        with self._lock:
            st = self._deployments.get((app, deployment))
            if st is None:
                return self._version, []
            return self._version, list(st.replicas)

    def get_deployment_limits(self, app: str, deployment: str
                              ) -> Dict[str, Any]:
        """Admission-control knobs the router enforces client-side
        (fetched alongside the replica set on a version change)."""
        with self._lock:
            st = self._deployments.get((app, deployment))
            if st is None:
                return {}
            return {
                "max_ongoing_requests": st.config.max_ongoing_requests,
                "max_queued_requests": getattr(
                    st.config, "max_queued_requests", -1),
            }

    def get_route_table(self) -> Dict[str, Tuple[str, str]]:
        with self._lock:
            return {info["route_prefix"]: (app, info["ingress"])
                    for app, info in self._apps.items()}

    def poll_update(self, known_version: int, timeout_s: float = 10.0) -> int:
        """Long-poll — reference _private/long_poll.py LongPollHost."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._version != known_version:
                    return self._version
            time.sleep(0.05)
        return known_version

    def get_serve_status(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "proxy": {"host": self._http_host, "port": self._http_port,
                          "ready": self._proxy_addr is not None},
                "proxies": {nid: list(addr) for nid, addr
                            in self._proxy_addrs.items()},
                "applications": {
                    app: {
                        "route_prefix": info["route_prefix"],
                        "ingress": info["ingress"],
                        "status": self._app_status(app),
                        "deployments": {
                            d: self._deployments[(app, d)].to_status()
                            for d in info["deployments"]},
                    } for app, info in self._apps.items()},
            }

    def _app_status(self, app: str) -> str:
        sts = [self._deployments[(app, d)].status
               for d in self._apps[app]["deployments"]]
        if all(s == "RUNNING" for s in sts):
            return "RUNNING"
        if any(s == "UNHEALTHY" for s in sts):
            return "UNHEALTHY"
        return "DEPLOYING"

    def get_proxy_address(self) -> Optional[Tuple[str, int]]:
        return self._proxy_addr

    def get_proxy_addresses(self) -> Dict[str, Tuple[str, int]]:
        """node_id -> bound (host, port) for every live proxy."""
        with self._lock:
            return dict(self._proxy_addrs)

    def get_grpc_address(self):
        """('disabled', None) when no grpc_port was configured — lets
        clients return immediately instead of polling out a deadline —
        else ('ok', addr_or_None_while_binding)."""
        if self._grpc_port is None:
            return ("disabled", None)
        return ("ok", self._grpc_addr)

    # -- reconciliation -----------------------------------------------------
    def _reconcile_loop(self):
        last_publish = 0.0
        while not self._shutting_down:
            try:
                self._ensure_proxy()
                self._reconcile_once()
                now = time.monotonic()
                if now - last_publish > 2.0:
                    last_publish = now
                    self._publish_status()
            except Exception:  # noqa: BLE001 — keep the loop alive
                import traceback
                traceback.print_exc()
            time.sleep(0.25)

    def _publish_status(self):
        """Mirror serve status into the conductor KV so out-of-band
        consumers (the dashboard) can render Serve apps without an
        actor-call path into this controller."""
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker
        if w is None:
            return
        try:
            w.conductor.notify("kv_put", "serve:status",
                               self.get_serve_status(), True, "serve")
        except Exception:  # noqa: BLE001 — conductor briefly away
            pass

    def _ensure_proxy(self):
        """Reconcile the proxy fleet with cluster topology: one
        ProxyActor pinned to every alive node (EveryNode), each polling
        the same route table (reference proxy.py:1111 per-node proxies +
        proxy_state.py). The head node's proxy keeps the configured
        port and carries gRPC; the rest bind ephemeral ports."""
        import ray_tpu
        from ray_tpu._private import worker as worker_mod
        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        from .proxy import ProxyActor

        # topology changes are rare: poll it on its own slow cadence
        # instead of burdening every 0.25s reconcile tick with a
        # conductor RPC (and the proxy-ready wait below)
        now = time.monotonic()
        if self._proxies and now - self._last_topology_check < 5.0:
            return
        self._last_topology_check = now
        w = worker_mod.global_worker
        try:
            nodes = w.conductor.call("nodes", timeout=5.0)
        except Exception:  # noqa: BLE001 — conductor briefly unreachable
            return
        alive = [n for n in nodes if n["alive"]]
        head_id = next((n["node_id"] for n in alive if n.get("head")), None)
        if self._proxy_location != "EveryNode":
            alive = [n for n in alive if n.get("head")]
        for n in alive:
            nid = n["node_id"]
            with self._lock:
                if nid in self._proxies or nid in self._proxy_pending:
                    continue
                self._proxy_pending.add(nid)
            # proxy startup (actor create + ready wait) runs OFF the
            # reconcile thread: a slow node must not stall replica
            # health checks and autoscaling for every app
            threading.Thread(
                target=self._create_proxy,
                args=(nid, nid == head_id,
                      (n.get("address") or [None])[0]),
                daemon=True, name=f"serve-proxy-create-{nid[:8]}").start()
        alive_ids = {n["node_id"] for n in alive}
        with self._lock:
            dead = [(x, self._proxies.pop(x))
                    for x in list(self._proxies) if x not in alive_ids]
            for nid, _ in dead:
                self._proxy_addrs.pop(nid, None)
        for _, actor in dead:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001 — died with its node
                pass

    def _create_proxy(self, nid: str, is_head: bool,
                      node_host: Optional[str]) -> None:
        import ray_tpu
        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        from .proxy import ProxyActor

        actor = None
        try:
            # non-head proxies bind wildcard (the head's configured host
            # may not exist on that machine) and advertise their node's
            # reachable address
            actor = ray_tpu.remote(ProxyActor).options(
                name=f"{PROXY_NAME}:{nid}", max_concurrency=32,
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    nid, soft=False)).remote(
                self._http_host if is_head else "0.0.0.0",
                self._http_port if is_head else 0,
                self._grpc_port if is_head else None,
                None if is_head else (node_host or self._http_host))
            addr = tuple(ray_tpu.get(actor.ready.remote(), timeout=60.0))
            grpc_addr = None
            if is_head and self._grpc_port is not None:
                ga = ray_tpu.get(actor.grpc_address.remote())
                grpc_addr = tuple(ga) if ga else None
        except Exception:  # noqa: BLE001 — node died mid-create; a
            actor = None   # later topology tick retries
        finally:
            with self._lock:
                self._proxy_pending.discard(nid)
                if actor is not None and not self._shutting_down:
                    self._proxies[nid] = actor
                    self._proxy_addrs[nid] = addr
                    if is_head:
                        self._proxy = actor
                        self._proxy_addr = addr
                        # the proxy skips busy ports — report the bound
                        self._http_host, self._http_port = addr
                        self._grpc_addr = grpc_addr
                    actor = None
        if actor is not None:  # shutdown raced the create: reap it
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                pass

    def _reconcile_once(self):
        import ray_tpu
        with self._lock:
            states = list(self._deployments.values())
        for st in states:
            self._autoscale(st)
            with self._lock:
                live = list(st.replicas)
                want = st.target_num_replicas
            # health checks (reference deployment_state.py check_health path)
            now = time.monotonic()
            if now - st.last_health_check > st.config.health_check_period_s:
                st.last_health_check = now
                healthy = []
                replica_metrics: Dict[str, Any] = {}
                for tag, handle in live:
                    # piggyback data-plane telemetry on the health
                    # cadence: both calls are submitted BEFORE waiting
                    # so the pass still costs one round-trip wait per
                    # replica, not two (latency/TTFT live in Prometheus;
                    # these counters surface in `serve status`)
                    try:
                        health_ref = handle.check_health.remote()
                        metrics_ref = handle.get_metrics.remote()
                        ray_tpu.get(health_ref,
                                    timeout=st.config.health_check_timeout_s)
                        healthy.append((tag, handle))
                    except Exception:  # noqa: BLE001 — replica is dead
                        try:
                            ray_tpu.kill(handle)
                        except Exception:  # noqa: BLE001
                            pass
                        continue
                    try:
                        replica_metrics[tag] = ray_tpu.get(
                            metrics_ref,
                            timeout=st.config.health_check_timeout_s)
                    except Exception:  # noqa: BLE001 — busy replica:
                        pass           # keep the stale snapshot
                with self._lock:
                    st.replica_metrics = {
                        t: replica_metrics.get(t, st.replica_metrics.get(t))
                        for t, _ in healthy
                        if replica_metrics.get(t)
                        or st.replica_metrics.get(t)}
                if len(healthy) != len(live):
                    with self._lock:
                        st.replicas = healthy
                        self._version += 1
                    live = healthy
            # scale up
            while len(live) < want:
                tag = f"{st.app_name}#{st.name}#{uuid.uuid4().hex[:6]}"
                try:
                    handle = self._start_replica(st, tag)
                except Exception:  # noqa: BLE001 — e.g. no resources yet
                    st.status = "DEPLOYING"
                    break
                live.append((tag, handle))
                with self._lock:
                    st.replicas = list(live)
                    self._version += 1
            # scale down (newest first, like the reference's pending-first)
            removed = []
            while len(live) > want:
                removed.append(live.pop())
            if removed:
                with self._lock:
                    st.replicas = list(live)
                    self._version += 1
                for tag, handle in removed:
                    self._stop_replica(handle, st.config)
            st.status = "RUNNING" if len(live) >= want else "DEPLOYING"

    def _start_replica(self, st: _DeploymentState, tag: str):
        import ray_tpu
        from .replica import ReplicaActor
        opts = dict(st.config.ray_actor_options or {})
        opts.setdefault("max_concurrency", st.config.max_ongoing_requests)
        handle = ray_tpu.remote(ReplicaActor).options(**opts).remote(
            tag, st.name, st.app_name, st.serialized_callable, st.init_args,
            st.config.user_config)
        # Block until constructed so a broken __init__ surfaces here.
        ray_tpu.get(handle.check_health.remote(), timeout=60.0)
        return handle

    def _stop_replica(self, handle, config: DeploymentConfig):
        import ray_tpu
        try:
            ray_tpu.get(handle.prepare_for_shutdown.remote(
                config.graceful_shutdown_timeout_s),
                timeout=config.graceful_shutdown_timeout_s + 5.0)
        except Exception:  # noqa: BLE001 — force-kill below either way
            pass
        try:
            ray_tpu.kill(handle)
        except Exception:  # noqa: BLE001
            pass

    # -- autoscaling --------------------------------------------------------
    def record_handle_metrics(self, app: str, deployment: str,
                              handle_id: str, inflight: float) -> None:
        """Reference serve/_private/autoscaling_state.py — handles push their
        queued+ongoing counts; the controller aggregates across handles."""
        with self._lock:
            st = self._deployments.get((app, deployment))
            if st is not None:
                st.handle_metrics[handle_id] = (inflight, time.monotonic())

    _METRICS_STALE_S = 3.0

    def _autoscale(self, st: _DeploymentState):
        cfg: Optional[AutoscalingConfig] = st.config.autoscaling_config
        if cfg is None or st.scaling_policy is None:
            return  # NOTE: runs even with zero replicas, else a
        # min_replicas=0 deployment that scaled to zero could never wake up.
        now = time.monotonic()
        with self._lock:
            st.handle_metrics = {
                h: (v, ts) for h, (v, ts) in st.handle_metrics.items()
                if now - ts < self._METRICS_STALE_S}
            handle_total = sum(v for v, _ in st.handle_metrics.values())
            # replica-reported queue depth (collected on the health
            # cadence): a deployment whose handles stopped reporting —
            # or that is driven through the HTTP proxy's own handle —
            # still autoscales on what its replicas actually hold
            replica_total = sum(
                float(m.get("inflight", 0))
                for m in st.replica_metrics.values())
        total = max(handle_total, replica_total)
        desired = int(math.ceil(total / cfg.target_ongoing_requests))
        # the shared hysteresis engine (serve/autoscale.ScalingPolicy)
        # owns the clamp + persistence gates — one "don't flap" core
        # for both this controller and the disagg tier loop
        st.target_num_replicas = st.scaling_policy.decide(
            desired, st.target_num_replicas, now)
