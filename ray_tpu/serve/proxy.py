"""HTTP proxy actor — analog of the reference's python/ray/serve/_private/
proxy.py (ProxyActor :1111, HTTPProxy.__call__ :836, proxy_request :423) +
proxy_router.py (longest-prefix route matching).

The reference embeds uvicorn; here an aiohttp server runs inside the actor on
its own thread/event loop. Replica calls are sync actor calls dispatched to a
thread pool so the event loop stays free."""
from __future__ import annotations

import asyncio
import json
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from .handle import (CONTROLLER_NAME, DeploymentHandle, RequestMetadata,
                     RequestShedError)
from .http_util import Request, coerce_response

MULTIPLEX_HEADER = "serve_multiplexed_model_id"


def _encode_chunk(item, sse: bool) -> bytes:
    """Wire form of one streamed chunk: SSE data-frames when the client
    asked for an event stream, raw bytes otherwise."""
    if isinstance(item, bytes):
        data = item
    elif isinstance(item, str):
        data = item.encode()
    else:
        data = json.dumps(item, default=str).encode()
    if sse:
        # one 'data:' field line per embedded newline, per the SSE spec —
        # a raw newline inside a data line would be dropped by compliant
        # event-stream parsers
        return b"".join(b"data: " + ln + b"\n"
                        for ln in data.split(b"\n")) + b"\n"
    return data


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 grpc_port: Optional[int] = None,
                 advertise_host: Optional[str] = None):
        # bind on `host`; report `advertise_host` (a non-head node's
        # reachable IP when binding a wildcard address) to clients
        self._host = host
        self._advertise_host = advertise_host or host
        self._port = port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._handles: Dict[Tuple[str, str], DeploymentHandle] = {}
        self._route_version = -1
        self._ready = threading.Event()
        self._bound_port: Optional[int] = None
        self._shutdown = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="proxy-call")
        self._grpc_server = None
        self._grpc_bound_port: Optional[int] = None
        threading.Thread(target=self._serve_thread, daemon=True,
                         name="serve-proxy-http").start()
        threading.Thread(target=self._route_poll_loop, daemon=True,
                         name="serve-proxy-routes").start()
        if grpc_port is not None:
            self._start_grpc(grpc_port)

    # -- control ------------------------------------------------------------
    def ready(self) -> Tuple[str, int]:
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("proxy HTTP server failed to start")
        return (self._advertise_host, self._bound_port)

    def grpc_address(self) -> Optional[Tuple[str, int]]:
        if self._grpc_bound_port is None:
            return None
        return (self._advertise_host, self._grpc_bound_port)

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        if self._grpc_server is not None:
            self._grpc_server.stop(grace=1.0)
        return True

    # -- gRPC ingress -------------------------------------------------------
    def _start_grpc(self, grpc_port: int) -> None:
        """Generic-handler gRPC ingress (reference serve gRPC proxy,
        python/ray/serve/_private/proxy.py gRPCProxy + serve.proto).
        No generated stubs: the service is registered dynamically with
        raw-bytes messages — Call (unary) and CallStreaming (server
        streaming); request bytes are a cloudpickled (args, kwargs) pair,
        routing metadata keys are 'application' and 'call_method'."""
        import grpc

        import cloudpickle as cp

        def meta_of(context) -> Tuple[str, str]:
            md = dict(context.invocation_metadata())
            return md.get("application", "default"), \
                md.get("call_method", "__call__")

        def resolve(context):
            app, method = meta_of(context)
            ingress = next((d for (a, d) in self._routes.values()
                            if a == app), None)
            if ingress is None:
                context.abort(grpc.StatusCode.NOT_FOUND,
                              f"no application named '{app}'")
            handle = self._handle_for(app, ingress)
            meta = RequestMetadata(call_method=method, app_name=app)
            return handle, meta

        def unary_call(request: bytes, context) -> bytes:
            handle, meta = resolve(context)
            args, kwargs = cp.loads(request)
            try:
                resp = handle._router.assign(meta, args, kwargs)
                return cp.dumps(resp.result(timeout_s=60.0))
            except RequestShedError as e:
                # admission-control shed: RESOURCE_EXHAUSTED is the
                # retryable overload code (the gRPC twin of the HTTP
                # handler's 503 + Retry-After)
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except Exception as e:  # noqa: BLE001 — surface as INTERNAL
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        def stream_call(request: bytes, context):
            handle, meta = resolve(context)
            args, kwargs = cp.loads(request)
            try:
                sresp = handle._router.assign_stream(meta, args, kwargs)
                for item in sresp:
                    yield cp.dumps(item)
                if sresp.kind == "value":  # plain method: one message
                    yield cp.dumps(sresp.value)
            except RequestShedError as e:
                context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
            except Exception as e:  # noqa: BLE001
                context.abort(grpc.StatusCode.INTERNAL,
                              f"{type(e).__name__}: {e}")

        service = grpc.method_handlers_generic_handler(
            "ray_tpu.serve.Ingress",
            {"Call": grpc.unary_unary_rpc_method_handler(unary_call),
             "CallStreaming":
                 grpc.unary_stream_rpc_method_handler(stream_call)})
        self._grpc_server = grpc.server(
            ThreadPoolExecutor(max_workers=16,
                               thread_name_prefix="proxy-grpc"))
        self._grpc_server.add_generic_rpc_handlers((service,))
        bound = self._grpc_server.add_insecure_port(
            f"{self._host}:{grpc_port}")
        if bound == 0 and grpc_port != 0:
            # grpc signals bind failure by returning 0 — fall back to an
            # ephemeral port rather than publishing (host, 0) as live.
            bound = self._grpc_server.add_insecure_port(f"{self._host}:0")
        if bound == 0:
            raise RuntimeError(
                f"could not bind gRPC ingress on {self._host} "
                f"(requested port {grpc_port})")
        self._grpc_bound_port = bound
        self._grpc_server.start()

    def _controller(self):
        import ray_tpu
        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _route_poll_loop(self):
        import ray_tpu
        while not self._shutdown.is_set():
            try:
                ctrl = self._controller()
                version = ray_tpu.get(ctrl.poll_update.remote(
                    self._route_version, 5.0), timeout=15.0)
                if version != self._route_version:
                    self._route_version = version
                    self._routes = ray_tpu.get(
                        ctrl.get_route_table.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001 — controller restarting
                self._shutdown.wait(1.0)

    # -- data plane ---------------------------------------------------------
    def _match_route(self, path: str) -> Optional[Tuple[str, str, str]]:
        """Longest-prefix match — reference proxy_router.py."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or prefix == "/":
                if best is None or len(norm) > len(best[0].rstrip("/")):
                    best = (prefix, app, ingress)
        return best

    def _handle_for(self, app: str, deployment: str) -> DeploymentHandle:
        key = (app, deployment)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(deployment, app)
        return self._handles[key]

    def _call_replica(self, app: str, ingress: str, req: Request,
                      route: str):
        """Every HTTP request rides the streaming path (reference: the
        proxy always calls handle_request_streaming, replica.py:470) —
        plain returns come back in the final reply with zero stream
        traffic, generator returns stream chunk-by-chunk."""
        handle = self._handle_for(app, ingress)
        meta = RequestMetadata(
            call_method="__call__", is_http=True, app_name=app, route=route,
            multiplexed_model_id=req.headers.get(MULTIPLEX_HEADER, ""))
        return handle._router.assign_stream(meta, (req,), {})

    def _call_and_open(self, app: str, ingress: str, req: Request,
                      route: str):
        """assign + first_event with a dead-replica retry: a request
        whose replica died before producing ANY event never executed to
        completion and is safe to re-route — the redeploy/drain window
        (reference proxy retries DeploymentUnavailable/actor-death
        errors against the refreshed replica set)."""
        from ray_tpu.exceptions import ActorDiedError

        last_err = None
        for attempt in range(3):
            sresp = self._call_replica(app, ingress, req, route)
            try:
                return sresp, sresp.first_event()
            except ActorDiedError as e:
                last_err = e
                handle = self._handle_for(app, ingress)
                handle._router._refresh(force=True)
        raise last_err

    def _serve_thread(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def dispatch(request: "web.Request") -> "web.Response":
            path = request.path
            if path == "/-/healthz":
                return web.Response(text="success")
            if path == "/-/routes":
                return web.json_response(
                    {p: f"{a}#{d}" for p, (a, d) in self._routes.items()})
            match = self._match_route(path)
            if match is None:
                return web.Response(
                    status=404,
                    text=f"no application matches path '{path}'; routes: "
                         f"{json.dumps(sorted(self._routes))}")
            prefix, app, ingress = match
            body = await request.read()
            req = Request(method=request.method, path=path,
                          query_string=request.query_string,
                          headers=dict(request.headers), body=body)
            req.headers.setdefault("x-request-id", uuid.uuid4().hex)
            try:
                sresp, first = await loop.run_in_executor(
                    self._pool,
                    self._call_and_open, app, ingress, req, prefix)
            except RequestShedError as e:
                # admission control shed: 503 + Retry-After, the
                # standard backpressure contract for HTTP clients
                return web.Response(
                    status=503,
                    headers={"Retry-After":
                             str(max(1, int(e.retry_after_s)))},
                    text=str(e))
            except Exception as e:  # noqa: BLE001 — surface as 500
                return web.Response(status=500, text=f"{type(e).__name__}: {e}")
            if first[0] == "value":
                status, headers, payload = coerce_response(first[1])
                from multidict import CIMultiDict

                # list-of-pairs headers preserve duplicates (Set-Cookie)
                hdrs = CIMultiDict(headers if isinstance(headers, list)
                                   else list(headers.items()))
                return web.Response(status=status, headers=hdrs,
                                    body=payload)
            # generator result: chunked transfer; SSE framing when the
            # client asked for text/event-stream
            sse = "text/event-stream" in request.headers.get("Accept", "")
            resp = web.StreamResponse(status=200)
            resp.headers["content-type"] = (
                "text/event-stream" if sse else "text/plain; charset=utf-8")
            resp.enable_chunked_encoding()
            await resp.prepare(request)
            _done = object()
            item = first[1] if first[0] == "chunk" else _done
            try:
                while item is not _done:
                    await resp.write(_encode_chunk(item, sse))
                    item = await loop.run_in_executor(
                        self._pool, lambda: next(sresp, _done))
            except Exception:  # noqa: BLE001 — replica died mid-stream:
                pass           # nothing valid left to write; close the wire
            await resp.write_eof()
            return resp

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", dispatch)

        async def run():
            runner = web.AppRunner(app)
            await runner.setup()
            port = self._port
            site = None
            for attempt in range(20):  # skip ports already in use
                try:
                    site = web.TCPSite(runner, self._host, port)
                    await site.start()
                    break
                except OSError:
                    if port == 0:  # ephemeral bind cannot EADDRINUSE
                        raise
                    port += 1
                    site = None
            if site is None:
                raise RuntimeError("could not bind proxy port")
            if port == 0:
                # ephemeral request (non-head per-node proxies): report
                # the port the kernel actually assigned
                port = site._server.sockets[0].getsockname()[1]
            self._bound_port = port
            self._ready.set()
            while not self._shutdown.is_set():
                await asyncio.sleep(0.2)
            await runner.cleanup()

        loop.run_until_complete(run())
