"""HTTP proxy actor — analog of the reference's python/ray/serve/_private/
proxy.py (ProxyActor :1111, HTTPProxy.__call__ :836, proxy_request :423) +
proxy_router.py (longest-prefix route matching).

The reference embeds uvicorn; here an aiohttp server runs inside the actor on
its own thread/event loop. Replica calls are sync actor calls dispatched to a
thread pool so the event loop stays free."""
from __future__ import annotations

import asyncio
import json
import threading
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional, Tuple

from .handle import CONTROLLER_NAME, DeploymentHandle, RequestMetadata
from .http_util import Request, coerce_response

MULTIPLEX_HEADER = "serve_multiplexed_model_id"


class ProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self._host = host
        self._port = port
        self._routes: Dict[str, Tuple[str, str]] = {}
        self._handles: Dict[Tuple[str, str], DeploymentHandle] = {}
        self._route_version = -1
        self._ready = threading.Event()
        self._bound_port: Optional[int] = None
        self._shutdown = threading.Event()
        self._pool = ThreadPoolExecutor(max_workers=32,
                                        thread_name_prefix="proxy-call")
        threading.Thread(target=self._serve_thread, daemon=True,
                         name="serve-proxy-http").start()
        threading.Thread(target=self._route_poll_loop, daemon=True,
                         name="serve-proxy-routes").start()

    # -- control ------------------------------------------------------------
    def ready(self) -> Tuple[str, int]:
        if not self._ready.wait(timeout=30.0):
            raise RuntimeError("proxy HTTP server failed to start")
        return (self._host, self._bound_port)

    def graceful_shutdown(self) -> bool:
        self._shutdown.set()
        return True

    def _controller(self):
        import ray_tpu
        return ray_tpu.get_actor(CONTROLLER_NAME)

    def _route_poll_loop(self):
        import ray_tpu
        while not self._shutdown.is_set():
            try:
                ctrl = self._controller()
                version = ray_tpu.get(ctrl.poll_update.remote(
                    self._route_version, 5.0), timeout=15.0)
                if version != self._route_version:
                    self._route_version = version
                    self._routes = ray_tpu.get(
                        ctrl.get_route_table.remote(), timeout=10.0)
            except Exception:  # noqa: BLE001 — controller restarting
                self._shutdown.wait(1.0)

    # -- data plane ---------------------------------------------------------
    def _match_route(self, path: str) -> Optional[Tuple[str, str, str]]:
        """Longest-prefix match — reference proxy_router.py."""
        best = None
        for prefix, (app, ingress) in self._routes.items():
            norm = prefix.rstrip("/") or ""
            if path == norm or path.startswith(norm + "/") or prefix == "/":
                if best is None or len(norm) > len(best[0].rstrip("/")):
                    best = (prefix, app, ingress)
        return best

    def _handle_for(self, app: str, deployment: str) -> DeploymentHandle:
        key = (app, deployment)
        if key not in self._handles:
            self._handles[key] = DeploymentHandle(deployment, app)
        return self._handles[key]

    def _call_replica(self, app: str, ingress: str, req: Request,
                      route: str):
        handle = self._handle_for(app, ingress)
        meta = RequestMetadata(
            call_method="__call__", is_http=True, app_name=app, route=route,
            multiplexed_model_id=req.headers.get(MULTIPLEX_HEADER, ""))
        resp = handle._router.assign(meta, (req,), {})
        return resp.result(timeout_s=60.0)

    def _serve_thread(self):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def dispatch(request: "web.Request") -> "web.Response":
            path = request.path
            if path == "/-/healthz":
                return web.Response(text="success")
            if path == "/-/routes":
                return web.json_response(
                    {p: f"{a}#{d}" for p, (a, d) in self._routes.items()})
            match = self._match_route(path)
            if match is None:
                return web.Response(
                    status=404,
                    text=f"no application matches path '{path}'; routes: "
                         f"{json.dumps(sorted(self._routes))}")
            prefix, app, ingress = match
            body = await request.read()
            req = Request(method=request.method, path=path,
                          query_string=request.query_string,
                          headers=dict(request.headers), body=body)
            req.headers.setdefault("x-request-id", uuid.uuid4().hex)
            try:
                result = await loop.run_in_executor(
                    self._pool,
                    self._call_replica, app, ingress, req, prefix)
            except Exception as e:  # noqa: BLE001 — surface as 500
                return web.Response(status=500, text=f"{type(e).__name__}: {e}")
            status, headers, payload = coerce_response(result)
            return web.Response(status=status, headers=headers, body=payload)

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", dispatch)

        async def run():
            runner = web.AppRunner(app)
            await runner.setup()
            port = self._port
            site = None
            for attempt in range(20):  # skip ports already in use
                try:
                    site = web.TCPSite(runner, self._host, port)
                    await site.start()
                    break
                except OSError:
                    port += 1
                    site = None
            if site is None:
                raise RuntimeError("could not bind proxy port")
            self._bound_port = port
            self._ready.set()
            while not self._shutdown.is_set():
                await asyncio.sleep(0.2)
            await runner.cleanup()

        loop.run_until_complete(run())
