"""Disaggregated prefill/decode serving: dedicated prefill replicas
stream KV blocks to decode replicas over the chunk fabric.

Why (the ROADMAP serving-envelope item, and the Gemma-on-TPU serving
envelope PAPERS.md: arXiv 2605.25645 measures): with prefill and decode
sharing one replica, a long prefill stalls every in-flight decode tick —
TTFT p99 and tokens/s both degrade under load. Splitting the phases
turns prefill into horizontally scalable compute-bound work and keeps
decode ticks free of head-of-line blocking:

- **PrefillServer** runs ``engine._prefill_paged`` behind the paged KV
  prefix cache (models/kvcache.py — shared system prompts still
  amortize), then publishes the prompt's KV rows plus the first token
  through ``util.chunks``: each leaf goes into the SENDER's own object
  store and only a metadata descriptor travels the control plane.
  Same no-full-copy invariant as the weight fabric and the MPMD
  activation channels — the bytes move sender -> receiver exactly once
  (shm zero-copy same-host, 64MB-ranged streaming across hosts), the
  conductor never holds payload, and the sender's ObjectRefs ARE the
  chunks' lifetime (``ack()`` releases them; a bounded retention window
  reaps unacked transfers).
- **DecodeServer** pulls the KV point-to-point with a ``ChunkFetcher``
  (shm-vs-rpc accounting) and ADOPTS it into its engine's decode slab
  via ``ContinuousBatchingEngine.adopt_prefill`` — an O(prompt_len)
  splice between ticks, never an O(pool) copy — so a decode replica
  never executes a prefill program at all (its ``_prefill_paged``
  compile cache stays flat; asserted in tests/test_disagg.py).
- **DisaggRouter** dispatches: the prefill replica is chosen by
  prefix-cache AFFINITY (a stable hash of the prompt's first cache
  block, so prompts sharing a system prompt land on the replica that
  already holds its KV), the decode replica by free-slot count; with no
  prefill tier configured it falls back to today's colocated
  single-replica path, bit-identical. On top it does **admission
  control + load shedding**: per-replica in-flight is bounded at
  capacity + ``max_queue_depth``; past the knob the request is REJECTED
  with a ``RequestShedError`` carrying ``retry_after_s`` — shed at the
  router, before the engine wedges.

On top of dispatch the router owns **request-level fault tolerance**
(the serving-plane failover invariant: an ACCEPTED request is never
silently dropped — it either streams to completion or sheds with an
attributed cause):

- every request records its prompt, its sampled-token history, and a
  per-attempt deadline; decode streams cross the actor boundary as
  chunked pulls (``DecodeServer.start_decode``/``next_tokens``) so the
  router always holds the tokens produced so far;
- on decode-replica death mid-stream the router re-runs prefill with
  the dead replica's tokens EXTENDING the prompt (the prefix cache
  makes the replay a suffix-only prefill) on a healthy prefill replica
  and resumes decode on a survivor — bit-identical to an uninterrupted
  greedy run, the correctness oracle;
- prefill death before its transfer is acked retries on another
  prefill replica (the dead process's chunk refs die with it — no
  leak by construction);
- attempts are bounded (``RAY_TPU_FAILOVER_ATTEMPTS`` extra attempts,
  default 2); exhaustion sheds with cause ``failover``, a request past
  its ``deadline_s`` sheds with cause ``deadline``.

Surfaces (the full treatment): ``util.state.disagg_status()`` +
``util.state.servefault_status()``, ``ray_tpu disagg`` / ``ray_tpu
servefault`` CLI, dashboard ``/api/disagg`` + ``/api/servefault`` +
SPA tabs, lazy Prometheus (``ray_tpu_disagg_kv_bytes_total{direction}``,
``ray_tpu_disagg_transfers_total``, ``ray_tpu_serve_shed_total``,
``ray_tpu_disagg_queue_depth``,
``ray_tpu_servefault_failovers_total{phase}``,
``ray_tpu_servefault_sheds_total{cause}``), ``disagg`` instant markers
in the merged timeline plus ``failover`` markers in its resilience
lane. Knobs: ``RAY_TPU_DISAGG_QUEUE_DEPTH`` (router backlog
bound per decode replica, default 8), ``RAY_TPU_DISAGG_RETRY_AFTER_S``
(shed hint, default 1.0), ``RAY_TPU_FAILOVER_ATTEMPTS`` (bounded
failover budget, default 2), ``RAY_TPU_MAX_ADOPTIONS_PER_TICK`` (decode
adoption cap, models/engine.py), plus the kvcache knobs on the prefill
tier. The open-loop acceptance benchmark lives in
``ray_tpu/bench_serve.py`` (``--chaos`` for the fault-injection run).
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, \
    Tuple

import numpy as np

from ray_tpu.exceptions import ActorError, WorkerCrashedError
from ray_tpu.observability import requests as reqtrace

from .autoscale import SlidingWindow, default_target_p99_ms
from .handle import RequestShedError, shed_counter

_SERVER_SEQ = itertools.count()

# Exception shapes that mean "the replica's process is gone" (actor
# death, worker crash, or the RPC plane losing the connection) — the
# failover wrapper removes the corpse from the replica set and retries
# elsewhere. Anything else is a REQUEST failure (bad KV layout, a bug):
# it still consumes a bounded failover attempt but the replica stays.
_DEATH_TYPES = (ActorError, WorkerCrashedError, ConnectionError,
                EOFError, OSError)


def _is_pool_exhausted(e: BaseException) -> bool:
    """An adapter-pool-exhausted failure (serve/lora.py
    LoraPoolExhausted) — matched by name because the exception may
    arrive re-wrapped across the actor boundary. It is a CAPACITY
    condition (every pool row pinned by in-flight requests), not a
    replica fault: the router sheds cause=capacity immediately instead
    of burning failover attempts replaying it onto the same full
    pools."""
    return "LoraPoolExhausted" in repr(e)


# Deterministic tenant-CONFIGURATION failures (unknown tenant, adapter
# rank over the pool ceiling, tenant tag against a pool-less replica):
# retrying cannot help — affinity re-routes to the same healthy
# replica and the error reproduces — and shedding would mislabel a
# client/operator mistake as a serving fault. The router re-raises
# them to the caller as the ValueError they are. Substring-matched
# because they may arrive re-wrapped across the actor boundary.
_LORA_CONFIG_ERRORS = ("no adapter registered for tenant",
                       "exceeds the pool's rank_max",
                       "has no lora_pool",
                       "has no adapter pool",
                       "does not fit this model's target")


def _is_lora_config_error(e: BaseException) -> bool:
    r = repr(e)
    if any(m in r for m in _LORA_CONFIG_ERRORS):
        return True
    # fabric source, tenant never published: the subscriber's registry
    # miss. Matched in two pieces (quoting around the name varies with
    # repr nesting across the actor boundary), scoped to lora/* names
    # so unrelated weight fetches keep their failover semantics.
    return "no committed version" in r and "lora/" in r


class ReplicaDeadError(RuntimeError):
    """A tier-replica call failed because the replica died; carries the
    tier/rid so the failover path can attribute and re-route."""

    def __init__(self, tier: str, rid: str, cause: BaseException):
        super().__init__(f"{tier} replica {rid} died: "
                         f"{type(cause).__name__}: {cause}")
        self.tier = tier
        self.rid = rid
        self.cause = cause

# ----------------------------------------------------- prometheus (lazy)
# Created on first component construction, never at import (the
# weights / kvcache / online pattern — rebound ONCE to a complete dict).

_metrics: Optional[Dict[str, Any]] = None
_metrics_lock = threading.Lock()


def disagg_metrics() -> Dict[str, Any]:
    global _metrics
    m = _metrics
    if m is not None:
        return m
    with _metrics_lock:
        if _metrics is None:
            from ray_tpu.util.metrics import Counter, Gauge

            _metrics = dict(
                kv_bytes=Counter(
                    "ray_tpu_disagg_kv_bytes_total",
                    "KV-block bytes moved between prefill and decode "
                    "replicas over the chunk fabric",
                    tag_keys=("direction",)),
                transfers=Counter(
                    "ray_tpu_disagg_transfers_total",
                    "completed prefill->decode KV transfers (counted "
                    "when the decode replica's fetch finishes)"),
                queue_depth=Gauge(
                    "ray_tpu_disagg_queue_depth",
                    "requests in flight through a disagg router "
                    "(executing + queued at its decode tier)",
                    tag_keys=("router",)))
    return _metrics


# Serving-plane fault-tolerance metrics, shared with the self-healer in
# serve/autoscale.py (one lazy group so every servefault number has one
# Prometheus home).
_sf_metrics: Optional[Dict[str, Any]] = None
_sf_metrics_lock = threading.Lock()


def servefault_metrics() -> Dict[str, Any]:
    global _sf_metrics
    m = _sf_metrics
    if m is not None:
        return m
    with _sf_metrics_lock:
        if _sf_metrics is None:
            from ray_tpu.util.metrics import Counter

            _sf_metrics = dict(
                failovers=Counter(
                    "ray_tpu_servefault_failovers_total",
                    "request failover attempts after a tier-replica "
                    "failure (phase=prefill|decode)",
                    tag_keys=("phase",)),
                sheds=Counter(
                    "ray_tpu_servefault_sheds_total",
                    "requests shed with an attributed cause "
                    "(capacity|deadline|failover|draining)",
                    tag_keys=("cause",)),
                replacements=Counter(
                    "ray_tpu_servefault_replacements_total",
                    "dead tier replicas replaced by the self-healer "
                    "(serve/autoscale.py)",
                    tag_keys=("tier",)),
                breaker_trips=Counter(
                    "ray_tpu_servefault_breaker_trips_total",
                    "replacement circuit-breaker OPEN transitions (a "
                    "host whose replicas die repeatedly stops getting "
                    "replacements)"))
    return _sf_metrics


def _worker():
    from ray_tpu._private import worker as worker_mod

    return worker_mod.global_worker


def _notify_event(event: Dict[str, Any]) -> None:
    """Best-effort instant marker into the conductor's disagg event log
    (the merged timeline's `disagg` lane). No-op without a cluster."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_disagg_event", dict(event))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _notify_kvplane(event: Dict[str, Any]) -> None:
    """Best-effort instant marker into the conductor's kvplane event
    log (the merged timeline's `kvplane` lane)."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_kvplane_event", dict(event))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _push_stats(component_id: str, stats: Dict[str, Any]) -> None:
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_disagg_stats", w.worker_id,
                           component_id, stats)
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _push_servefault(component_id: str, stats: Dict[str, Any]) -> None:
    """Servefault snapshot -> conductor aggregate (state API, CLI,
    /api/servefault, and the one-set-of-numbers check read it)."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_servefault_stats", w.worker_id,
                           component_id, stats)
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _notify_resilience(event: Dict[str, Any]) -> None:
    """Failovers are recovery events: mirror them into the resilience
    event log (the merged timeline's resilience lane, beside the PR-4
    preemption/restart markers)."""
    w = _worker()
    if w is None:
        return
    try:
        w.conductor.notify("report_resilience_event", dict(event))
    except Exception:  # noqa: BLE001 — cluster shutting down
        pass


def _call(target: Any, method: str, *args, block: bool = True, **kw):
    """Invoke `method` on a local component or a ray_tpu actor handle
    (the router accepts either, so tests and the load harness can run
    replicas in-process while deployments run them as actors)."""
    fn = getattr(target, method)
    remote = getattr(fn, "remote", None)
    if remote is not None:
        import ray_tpu

        ref = remote(*args, **kw)
        return ray_tpu.get(ref) if block else ref
    return fn(*args, **kw)


# ------------------------------------------------------------ prefill tier

class PrefillServer:
    """One prefill replica: compute-bound prefill behind the prefix
    cache, KV rows published as sender-owned chunks.

    ``prefill()`` returns a metadata-only record (safe to route through
    actors/the control plane): the first token, its logprob score, the
    prefix-cache outcome, and the chunk descriptor a DecodeServer
    fetches the KV from. The prompt's cache pins are released as soon as
    the KV is exported — blocks stay cached for future lookups."""

    def __init__(self, params: Any, config: Any, *,
                 prefix_cache: bool = True,
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 kv_int8: Optional[bool] = None,
                 retain: int = 32,
                 server_id: Optional[str] = None,
                 chaos: Optional[str] = None,
                 chaos_replica: int = 0,
                 lora: Any = None,
                 lora_pool_slots: Optional[int] = None,
                 lora_rank_max: Optional[int] = None,
                 kvplane: Optional[bool] = None,
                 kvplane_arena_bytes: Optional[int] = None):
        from ray_tpu.models.generate import _model_fns
        from ray_tpu.models.kvcache import (PagedKVCache,
                                            kv_int8_default,
                                            resolve_pool_config)

        import jax.numpy as jnp

        from ray_tpu.resilience.chaos import serve_monkey_from_spec
        from ray_tpu.util.chunks import local_machine_id

        from .lora import build_pool

        self.params = params
        self.config = config
        self.server_id = server_id or \
            f"pf-{os.getpid()}-{next(_SERVER_SEQ)}"
        self.machine = local_machine_id()
        # scripted fault injection (resilience/chaos.py kill_replica):
        # meaningful on ACTOR replicas — the fire is an os._exit
        self._chaos = serve_monkey_from_spec(chaos, "prefill",
                                             chaos_replica)
        # int8 KV blocks (models/kvcache.py): halve the pool's bytes
        # per block -> doubled default pool -> higher prefix residency
        # on the tier that actually owns prefix reuse
        if kv_int8 is None:
            kv_int8 = kv_int8_default()
        self.kv_int8 = bool(kv_int8)
        block_size, pool_blocks = resolve_pool_config(
            config, kv_block_size, kv_pool_blocks, int8=self.kv_int8)
        self.kv_cache: Optional[PagedKVCache] = (
            PagedKVCache(config, block_size=block_size,
                         num_blocks=pool_blocks, int8=self.kv_int8)
            if prefix_cache else None)
        # global KV plane (serve/kvplane.py): the tier-2 host arena
        # catches HBM-evicted blocks instead of letting them die, and
        # tier 3 publishes cold hot-prompt prefixes to the chunk
        # fabric under the conductor's prefix directory
        from .kvplane import HostArena, kvplane_enabled
        if kvplane is None:
            kvplane = kvplane_enabled()
        self.kvplane = bool(kvplane) and self.kv_cache is not None
        self.arena: Optional[HostArena] = None
        if self.kvplane:
            self.arena = HostArena(max_bytes=kvplane_arena_bytes,
                                   replica=self.server_id)
            self.kv_cache.attach_arena(self.arena)
        # multi-tenant LoRA (serve/lora.py): prefill runs under each
        # request's tenant adapter, so the prefill tier pages adapters
        # exactly like the decode tier; an adapter hot-swap flushes
        # that tenant's (namespace-keyed) prefix-cache entries
        self.lora_pool = build_pool(config, lora, slots=lora_pool_slots,
                                    rank_max=lora_rank_max)
        if self.lora_pool is not None and self.kv_cache is not None:
            # namespaces are (tenant, version)-stamped — correctness
            # never needs this flush; it eagerly reclaims the
            # superseded version's blocks
            self.lora_pool.add_swap_listener(
                lambda tenant, old, _p=self.lora_pool:
                self.kv_cache.invalidate(
                    namespace=_p.cache_namespace(tenant, old)))
        probe = _model_fns(config)[1](config, 1, max_len=1)
        shape = probe[0]["k"].shape  # [1, 1, H, hd]
        self._empty_prefix = jnp.zeros(
            (len(probe), 0) + shape[2:], probe[0]["k"].dtype)
        # retention bounds how many unacked transfers this server keeps
        # alive; size it past the decode tier's admitted bound
        # (decode_replicas * (max_batch + queue_depth)) — transfers are
        # held from publish until the router's post-decode ack, and
        # prefix affinity can route all of them here, so a smaller
        # window reaps chunks a decode replica is about to fetch
        self._retain = max(1, int(retain))
        self._lock = threading.Lock()
        # transfer_id -> chunk refs; holding them IS the chunks'
        # lifetime (ack() or retention-window reap drops them)
        self._held: "OrderedDict[str, List[Any]]" = OrderedDict()
        # tier-3 holder state: digest -> (namespace, chunk refs). The
        # refs ARE the published prefix's lifetime — keep-last-K so one
        # replica can never pin unbounded fabric bytes; evicting a
        # digest retracts its directory entry. _t3_known throttles
        # re-export attempts (committed OR lost to a racing holder).
        self._t3_refs: "OrderedDict[str, tuple]" = OrderedDict()
        self._t3_known: "OrderedDict[str, bool]" = OrderedDict()
        self._t3_keep = 8
        self._kvp_stats = {k: 0 for k in (
            "tier3_publishes", "tier3_adopts", "tier3_adopted_blocks",
            "tier3_reused_tokens", "tier3_fetched_bytes",
            "evict_storms", "storm_evicted_blocks")}
        self._seq = itertools.count()
        self._stats = {k: 0 for k in (
            "prefills", "prefilled_tokens", "reused_tokens",
            "published_transfers", "published_bytes", "acked",
            "reaped_unacked")}
        self._last_push = 0.0
        disagg_metrics()  # lazy registration before the first event

    # ---------------------------------------------------------- data plane

    def prefill(self, prompt_tokens,
                tenant: Optional[str] = None,
                kvplane_hint: Optional[Dict[str, Any]] = None
                ) -> Dict[str, Any]:
        """Prefill one prompt (suffix-only on a cache hit) and publish
        its KV rows. Returns the transfer record for a DecodeServer.
        `tenant` (multi-tenant LoRA): prefill under that tenant's
        adapter — paged through this server's pool — with the prefix
        cache keyed by (tenant, prompt); the record carries the tag so
        the decode tier adopts under the same adapter.
        `kvplane_hint` (serve/kvplane.py): a prefix-directory entry
        whose holder the router could not dispatch to — this replica
        fetches the published prefix over the transfer plane and
        adopts it BEFORE the cache lookup, so the prefill is
        suffix-only anyway (a failed fetch just prefills from scratch:
        tier 3 is an accelerator, not a dependency)."""
        from ray_tpu.models.engine import _prefill_with_cache
        from ray_tpu.util import chunks

        if self._chaos is not None:
            self._chaos.on_request()  # may os._exit (kill_replica)
            storm = self._chaos.take_storm()
            if storm and self.kv_cache is not None:
                # scripted eviction storm (chaos evict_storm): with the
                # arena attached the evicted blocks SPILL to tier 2
                # instead of dying — the chaos test's whole point
                evicted = self.kv_cache.force_evict(storm)
                with self._lock:
                    self._kvp_stats["evict_storms"] += 1
                    self._kvp_stats["storm_evicted_blocks"] += evicted
                _notify_kvplane({"kind": "evict_storm",
                                 "replica": self.server_id,
                                 "blocks": evicted,
                                 "requested": storm})
        prompt = np.asarray(prompt_tokens, np.int32).reshape(1, -1)
        plen = prompt.shape[1]
        if plen < 1:
            raise ValueError("empty prompt")
        adapter = None
        namespace = None
        if tenant is not None:
            if self.lora_pool is None:
                raise ValueError(
                    f"request for tenant {tenant!r} but this prefill "
                    f"server has no adapter pool (lora= ctor arg)")
            adapter, aver = self.lora_pool.adapter_slice(
                self.lora_pool.acquire(tenant), with_version=True)
            namespace = self.lora_pool.cache_namespace(tenant, aver)
        kvp_info: Dict[str, Any] = {}
        if self.arena is not None:
            # bracket the prefill: tier-2 re-adoptions inside the cache
            # lookup accumulate into this request's attribution
            self.arena.begin_request()
        if kvplane_hint is not None and self.kvplane \
                and _worker() is not None:
            t3 = self._adopt_t3(kvplane_hint, prompt[0], namespace)
            if t3 is not None:
                kvp_info["tier3"] = t3
        try:
            ck, cv, table, first, score, outcome, reused, suffix_len = \
                _prefill_with_cache(self.params, self.config,
                                    self.kv_cache, prompt,
                                    self._empty_prefix, adapter=adapter,
                                    namespace=namespace)
        finally:
            if adapter is not None:
                # the adapter pin covers exactly the prefill compute;
                # refcount-0 adapters stay resident for the next
                # request (the pool's LRU owns reclamation)
                self.lora_pool.release(tenant)
        if self.kv_cache is not None:
            # pins drop NOW: the KV is exported below, and refcount-0
            # blocks stay cached for the next prompt's lookup
            self.kv_cache.release(table)
        if self.arena is not None:
            t2 = self.arena.end_request()
            if t2.get("blocks"):
                kvp_info["tier2"] = t2
        # the transfer payload: exactly the prompt's KV rows, host-side
        # (this is the ONLY materialization outside the fill itself —
        # the same single-copy the colocated splice reads on-device)
        kv_k = np.asarray(ck[:, :plen])
        kv_v = np.asarray(cv[:, :plen])
        del ck, cv
        rec: Dict[str, Any] = {
            "transfer_id": f"{self.server_id}-{next(self._seq)}",
            "plen": plen, "first_token": first, "score": score,
            "outcome": outcome, "reused_tokens": int(reused),
            "prefill_server": self.server_id,
            # the prompt's actual tokens ride the (metadata) record so
            # the decode tier's speculative proposer drafts from the
            # same context the colocated engine would — tiny next to
            # the KV payload, and the adopting engine's n-gram lookup
            # is useless over the zero placeholder prompt otherwise
            "prompt_tokens": [int(t) for t in prompt[0]],
        }
        if tenant is not None:
            rec["tenant"] = tenant
        if kvp_info:
            # rides the metadata record back to the router, which turns
            # it into kvplane_tier2/3_fetch flight-recorder phases
            rec["kvplane"] = kvp_info
        nbytes = int(kv_k.nbytes + kv_v.nbytes)
        w = _worker()
        if w is not None:
            refs, desc = chunks.put_tree(w, {"k": kv_k, "v": kv_v})
            rec["kv"] = desc
            reaped = []
            with self._lock:
                self._held[rec["transfer_id"]] = refs
                while len(self._held) > self._retain:
                    reaped.append(self._held.popitem(last=False))
                self._stats["reaped_unacked"] += len(reaped)
        else:
            # clusterless (unit tests / in-process harness): the arrays
            # ride the record directly — no chunk plane to publish to
            rec["kv_inline"] = (kv_k, kv_v)
        # send is counted for BOTH paths: the receiver counts recv for
        # inline adoptions too, and a consumer cross-checking
        # send == recv must see the totals agree in either mode
        disagg_metrics()["kv_bytes"].inc(
            nbytes, tags={"direction": "send"})
        with self._lock:
            self._stats["prefills"] += 1
            self._stats["prefilled_tokens"] += suffix_len
            self._stats["reused_tokens"] += int(reused)
            self._stats["published_transfers"] += 1
            self._stats["published_bytes"] += nbytes
        _notify_event({"kind": "kv_publish", "server": self.server_id,
                       "transfer_id": rec["transfer_id"],
                       "bytes": nbytes, "plen": plen,
                       "outcome": outcome})
        if w is not None and self.kvplane:
            self._maybe_publish_t3(prompt[0], namespace)
        self.publish_telemetry()
        return rec

    # -------------------------------------------- global KV plane (tier 3)

    def _adopt_t3(self, entry: Dict[str, Any], tokens,
                  namespace: Optional[str]) -> Optional[Dict[str, Any]]:
        """Fetch a directory entry's published prefix over the chunk
        fabric and adopt it into the HBM pool ahead of the lookup.
        Returns the fetch attribution (for the flight recorder) or
        None when nothing crossed the wire."""
        from . import kvplane as kvp

        t0 = time.perf_counter()
        try:
            adopted, fst = kvp.fetch_and_adopt(
                _worker(), self.kv_cache, entry, tokens, namespace)
        except Exception:  # noqa: BLE001 — never fail the prefill
            return None
        ms = (time.perf_counter() - t0) * 1e3
        fetched = int(fst.get("fetched_bytes", 0))
        reused = int(adopted) * self.kv_cache.block_size
        with self._lock:
            if adopted:
                self._kvp_stats["tier3_adopts"] += 1
                self._kvp_stats["tier3_adopted_blocks"] += int(adopted)
                self._kvp_stats["tier3_reused_tokens"] += reused
            self._kvp_stats["tier3_fetched_bytes"] += fetched
        if adopted:
            _notify_kvplane({"kind": "tier3_adopt",
                             "replica": self.server_id,
                             "blocks": int(adopted),
                             "tokens": reused, "nbytes": fetched,
                             "namespace": namespace})
        if not adopted and not fetched:
            return None
        return {"blocks": int(adopted), "tokens": reused,
                "nbytes": fetched, "ms": round(ms, 3)}

    def _maybe_publish_t3(self, tokens, namespace: Optional[str]
                          ) -> None:
        """Publish the prompt's longest cached full-block prefix to
        tier 3 — chunk-fabric objects plus the conductor's prefix
        directory commit — at most once per digest from this replica.
        The held refs are the published object's lifetime: keep-last-K,
        and an evicted digest retracts its directory entry so lookups
        stop routing to bytes that are gone. Best-effort throughout:
        tier 3 is an accelerator, never a dependency."""
        from ray_tpu.models.kvcache import prefix_digests

        from . import kvplane as kvp

        if not kvp.directory_enabled() or self.kv_cache is None:
            return
        digs = prefix_digests(tokens, self.kv_cache.block_size,
                              namespace)
        if len(digs) < kvp.t3_min_blocks():
            return  # prompt too short to ever clear the publish floor
        head = digs[0]  # longest chain — the dedup/throttle key
        with self._lock:
            if head in self._t3_known:
                self._t3_known.move_to_end(head)
                return
        w = _worker()
        if w is None:
            return
        try:
            out = kvp.publish_prefix(w, self.kv_cache, tokens,
                                     namespace, self.server_id,
                                     machine=self.machine)
        except Exception:  # noqa: BLE001 — directory outage
            return
        dropped: List[tuple] = []
        with self._lock:
            self._t3_known[head] = out is not None
            while len(self._t3_known) > 4 * self._t3_keep:
                self._t3_known.popitem(last=False)
            if out is not None:
                digest_hex, refs = out
                self._t3_refs[digest_hex] = (namespace, refs)
                self._kvp_stats["tier3_publishes"] += 1
                while len(self._t3_refs) > self._t3_keep:
                    old_digest, (old_ns, _refs) = \
                        self._t3_refs.popitem(last=False)
                    dropped.append((old_digest, old_ns))
        for old_digest, old_ns in dropped:
            try:
                # the refs just died — retract the directory entry so
                # lookups stop routing fetches at a gone object
                w.conductor.call("kvplane_unpublish", old_ns or "",
                                 old_digest, timeout=5.0)
            except Exception:  # noqa: BLE001 — best-effort retract
                pass

    def kvplane_stats(self) -> Dict[str, Any]:
        """This replica's kvplane snapshot (tier-2 arena + tier-3
        holder counters + per-caller fabric attribution) — one
        component of the conductor's get_kvplane_stats aggregate."""
        from ray_tpu.util import chunks

        s: Dict[str, Any] = {"role": "prefill",
                             "server_id": self.server_id,
                             "enabled": self.kvplane}
        if self.arena is not None:
            s.update(self.arena.stats())
        with self._lock:
            s.update(self._kvp_stats)
            s["t3_held_refs"] = len(self._t3_refs)
        s["fabric"] = chunks.caller_totals("kvplane")
        return s

    def set_retention(self, retain: int) -> None:
        """Raise the retention window (routers push the decode tier's
        admitted bound at construction so the default can never reap an
        in-flight transfer); never shrinks below the constructor
        value."""
        with self._lock:
            self._retain = max(self._retain, int(retain))

    def ack(self, transfer_id: str) -> bool:
        """Receiver finished fetching: drop the chunks' refs (their
        lifetime). Returns False if retention already reaped them."""
        with self._lock:
            held = self._held.pop(transfer_id, None)
            if held is not None:
                self._stats["acked"] += 1
        return held is not None

    def describe(self) -> Dict[str, Any]:
        """Registration record for a router: identity + host (the
        decode-side placement-affinity input)."""
        return {"server_id": self.server_id, "role": "prefill",
                "machine": self.machine,
                "lora": self.lora_pool is not None,
                "kvplane": self.kvplane,
                # the router computes directory digests with OUR block
                # size — digest chains only match when they agree
                "kv_block_size": (self.kv_cache.block_size
                                  if self.kv_cache is not None
                                  else None)}

    def publish_adapter(self, tenant: str,
                        adapter: Dict[str, Any]) -> int:
        """Publish/replace a tenant's adapter on this replica's LOCAL
        source (actor-friendly — the in-process twin of a weight-fabric
        publish; fabric-backed pools take publishes through
        serve.lora.publish_adapter instead). The pool sees the tenant
        dirty and hot-swaps on the next acquire."""
        if self.lora_pool is None:
            raise ValueError("this prefill server has no adapter pool")
        return int(self.lora_pool.source.publish(tenant, adapter))

    def refresh_adapter(self, tenant: str) -> bool:
        """Force the resident adapter to the newest published version
        now (the dirty flag does it lazily on the next request)."""
        if self.lora_pool is None:
            return False
        return self.lora_pool.refresh(tenant)

    def reset_chaos_counts(self) -> bool:
        """Zero the chaos monkey's request/token counters so a
        `kill_replica at=request:N` plan counts from the MEASURED
        phase, not from warm-up traffic (bench_serve calls this at
        measurement start)."""
        if self._chaos is not None:
            self._chaos.reset_counts()
        return self._chaos is not None

    def invalidate_prefix_cache(self) -> bool:
        """Drop the whole prefix index (every namespace). bench_serve's
        bit-identity verdict calls it before the sequential re-runs so
        they re-prefill cache-cold — the re-check then covers the
        prefill path too, instead of replaying whatever the mixed run
        cached."""
        if self.kv_cache is None:
            return False
        self.kv_cache.invalidate()
        return True

    def prepare_for_shutdown(self, timeout_s: float = 30.0) -> bool:
        """Grace drain (the serve/replica.py shape, reused by autoscale
        scale-down): wait until every published transfer has been acked
        — a decode replica may still be fetching our chunks — then
        report whether the drain completed. The chunks' refs are this
        object's lifetime either way; the caller frees them by dropping
        the server."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            with self._lock:
                held = len(self._held)
            if held == 0 or time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        # retract this holder's directory entries: the tier-3 refs die
        # with the replica, so lookups must stop routing fetches here
        # (a stale entry is only a wasted fetch, but why leave one)
        w = _worker()
        with self._lock:
            t3 = list(self._t3_refs.items())
            self._t3_refs.clear()
        if w is not None:
            for digest_hex, (ns, _refs) in t3:
                try:
                    w.conductor.call("kvplane_unpublish", ns or "",
                                     digest_hex, timeout=5.0)
                except Exception:  # noqa: BLE001 — conductor gone too
                    pass
        self.publish_telemetry(force=True)
        return held == 0

    # ------------------------------------------------------------ telemetry

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            s["held_transfers"] = len(self._held)
        s["role"] = "prefill"
        s["server_id"] = self.server_id
        if self.kv_cache is not None:
            s["prefix_cache"] = self.kv_cache.stats()
        if self.lora_pool is not None:
            s["lora"] = self.lora_pool.stats()
        return s

    def kv_stats(self) -> Dict[str, Any]:
        """Engine-shaped snapshot for the kvcache surface (the prefill
        tier is where prefix reuse happens under disaggregation)."""
        s: Dict[str, Any] = (self.kv_cache.stats() if self.kv_cache
                             else {"enabled": False})
        with self._lock:
            s.update(engine_id=self.server_id, phase="prefill",
                     prefill_calls=self._stats["prefills"],
                     admitted=self._stats["prefills"],
                     prefill_admitted=self._stats["prefills"],
                     adopted=0)
        return s

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        _push_stats(self.server_id, self.stats())
        if self.lora_pool is not None:
            self.lora_pool.publish_telemetry(force=force)
        w = _worker()
        if w is None:
            if self.kv_cache is not None:
                self.kv_cache.drain_events()
            if self.arena is not None:
                self.arena.drain_events()
            return
        try:
            w.conductor.notify("report_kvcache_stats", w.worker_id,
                               self.server_id, self.kv_stats())
            if self.kv_cache is not None:
                for ev in self.kv_cache.drain_events():
                    ev.setdefault("engine", self.server_id)
                    w.conductor.notify("report_kvcache_event", ev)
            if self.kvplane:
                w.conductor.notify("report_kvplane_stats", w.worker_id,
                                   self.server_id,
                                   self.kvplane_stats())
                for ev in self.arena.drain_events():
                    w.conductor.notify("report_kvplane_event", ev)
        except Exception:  # noqa: BLE001 — cluster shutting down
            pass


# ------------------------------------------------------------- decode tier

class _CountedStream:
    """Iterates an adopted TokenStream and folds the drained token count
    into the owning DecodeServer's ``decoded_tokens`` (in the finally, so
    an abandoned/failed stream still accounts what it actually yielded).
    Everything else proxies to the underlying stream."""

    def __init__(self, server: "DecodeServer", stream: Any):
        self._server = server
        self._stream = stream

    def __iter__(self):
        n = 0
        try:
            for tok in self._stream:
                n += 1
                yield tok
        finally:
            self._server._count_decoded(n)

    def __getattr__(self, name: str) -> Any:
        return getattr(self._stream, name)


class DecodeServer:
    """One decode replica: a prefix-cache-DISABLED batching engine that
    only ever adopts prefilled KV — it never runs a prefill program
    (``prefill_programs()`` reports this process's `_prefill_paged`
    compile-cache size so tests can assert it stays flat)."""

    def __init__(self, params: Any, config: Any, *,
                 max_batch: int = 8,
                 server_id: Optional[str] = None,
                 chaos: Optional[str] = None,
                 chaos_replica: int = 0,
                 lora: Any = None,
                 lora_pool_slots: Optional[int] = None,
                 lora_rank_max: Optional[int] = None,
                 **engine_kw):
        from ray_tpu.models.engine import ContinuousBatchingEngine

        from ray_tpu.resilience.chaos import serve_monkey_from_spec
        from ray_tpu.util.chunks import local_machine_id

        from .lora import build_pool

        engine_kw.setdefault("prefix_cache", False)
        # multi-tenant LoRA: the decode tick applies each slot's
        # adapter, so the decode tier pages adapters through its own
        # pool (the engine pins at adoption, releases at slot-free)
        self.lora_pool = build_pool(config, lora, slots=lora_pool_slots,
                                    rank_max=lora_rank_max)
        if self.lora_pool is not None:
            engine_kw.setdefault("lora_pool", self.lora_pool)
        self.engine = ContinuousBatchingEngine(params, config,
                                               max_batch=max_batch,
                                               **engine_kw)
        self.server_id = server_id or \
            f"dec-{os.getpid()}-{next(_SERVER_SEQ)}"
        self.machine = local_machine_id()
        self._chaos = serve_monkey_from_spec(chaos, "decode",
                                             chaos_replica)
        self._lock = threading.Lock()
        # open chunked-pull streams (start_decode/next_tokens): handle
        # -> [TokenStream, last-activity]. Done streams clean themselves
        # up; abandoned ones (a router that shed on deadline mid-pull)
        # are reaped by IDLE age — every pull refreshes the stamp, so a
        # slow client's long stream is never reaped mid-request — and a
        # handle can never leak an engine request object forever.
        self._streams: Dict[str, List[Any]] = {}
        self._stats = {k: 0 for k in (
            "transfers", "kv_fetched_bytes", "shm_bytes", "rpc_bytes",
            "chunks_local", "decoded_tokens")}
        self._last_push = 0.0
        disagg_metrics()

    _STREAM_REAP_S = 600.0

    # ---------------------------------------------------------- data plane

    def _adopt(self, rec: Dict[str, Any], max_new_tokens: int,
               eos_token: Optional[int], timeout_s: float):
        from ray_tpu.util import chunks

        if self._chaos is not None:
            self._chaos.on_request()  # may os._exit (kill_replica)
        t_fetch0 = time.perf_counter()
        desc = rec.get("kv")
        if desc is not None:
            w = _worker()
            if w is None:
                raise RuntimeError(
                    "a chunk-published transfer needs a live cluster "
                    "(ray_tpu.init) on the decode side")
            fetcher = chunks.ChunkFetcher(w, caller="kv")
            tree = chunks.fetch_tree(w, desc, fetcher)
            kv_k, kv_v = tree["k"], tree["v"]
            acc = fetcher.stats()
        else:
            kv_k, kv_v = rec["kv_inline"]
            acc = {"chunks_local": 2, "chunks_fetched": 0,
                   "fetched_bytes": 0, "shm_bytes": 0, "rpc_bytes": 0}
        fetch_ms = (time.perf_counter() - t_fetch0) * 1e3
        nbytes = int(kv_k.nbytes + kv_v.nbytes)
        # adopt (which VALIDATES length bounds and KV layout) before any
        # accounting: a rejected adoption must not leave transfers >
        # adopted or a kv_transfer marker with no decode behind it — the
        # surfaces assert one set of numbers
        stream = self.engine.adopt_prefill(
            rec["plen"], rec["first_token"], kv_k, kv_v,
            max_new_tokens, eos_token, score=rec.get("score", 0.0),
            cache_outcome=rec.get("outcome"),
            reused_tokens=rec.get("reused_tokens", 0),
            adapter_id=rec.get("tenant"),
            prompt_tokens=rec.get("prompt_tokens"),
            timeout_s=timeout_s)
        with self._lock:
            self._stats["transfers"] += 1
            self._stats["kv_fetched_bytes"] += acc["fetched_bytes"]
            self._stats["shm_bytes"] += acc["shm_bytes"]
            self._stats["rpc_bytes"] += acc["rpc_bytes"]
            self._stats["chunks_local"] += acc["chunks_local"]
        m = disagg_metrics()
        m["transfers"].inc()
        m["kv_bytes"].inc(nbytes, tags={"direction": "recv"})
        _notify_event({"kind": "kv_transfer", "server": self.server_id,
                       "transfer_id": rec.get("transfer_id"),
                       "bytes": nbytes, "plen": rec["plen"],
                       "shm_bytes": acc["shm_bytes"],
                       "rpc_bytes": acc["rpc_bytes"],
                       "outcome": rec.get("outcome")})
        # flight recorder: in-process routers have the request trace
        # active on THIS thread (the open kv_transfer span absorbs the
        # fetch breakdown); an actor-mode replica has no thread-local
        # and pushes the breakdown as a remote child phase instead
        rt = rec.get("_reqtrace")
        if reqtrace.current_trace() is not None:
            reqtrace.annotate(kv_fetch_ms=round(fetch_ms, 3),
                              kv_bytes=nbytes,
                              shm_bytes=int(acc["shm_bytes"]),
                              rpc_bytes=int(acc["rpc_bytes"]),
                              chunks_local=int(acc["chunks_local"]))
        elif isinstance(rt, dict) and rt.get("request_id"):
            reqtrace.push_remote_phase(
                rt["request_id"], "kv_transfer_remote", fetch_ms,
                attempt=int(rt.get("attempt", 1)),
                server=self.server_id, kv_bytes=nbytes,
                shm_bytes=int(acc["shm_bytes"]),
                rpc_bytes=int(acc["rpc_bytes"]))
        return stream

    def stream_from(self, rec: Dict[str, Any], max_new_tokens: int,
                    eos_token: Optional[int] = None,
                    timeout_s: float = 120.0):
        """Adopt a transfer and return the live token stream (in-process
        callers only — streams do not cross the actor boundary). The
        stream proxies the underlying TokenStream (``cache_outcome``
        etc.) and folds drained tokens into ``decoded_tokens`` so the
        streaming path reports the same one set of numbers as
        ``decode_from``."""
        return _CountedStream(
            self, self._adopt(rec, max_new_tokens, eos_token, timeout_s))

    def decode_from(self, rec: Dict[str, Any], max_new_tokens: int,
                    eos_token: Optional[int] = None,
                    timeout_s: float = 120.0) -> List[int]:
        """Adopt a transfer and decode it to completion (actor-friendly:
        returns the full token list, first token included)."""
        stream = self._adopt(rec, max_new_tokens, eos_token, timeout_s)
        toks = list(stream)
        self._count_decoded(len(toks))
        return toks

    # ------------------------------------------- chunked-pull streaming
    # Streams cannot cross the actor boundary, but a blocking
    # decode_from loses every already-produced token when the replica
    # dies mid-request. The router therefore pulls tokens in bounded
    # chunks: it always holds the history produced so far, which is
    # exactly what the failover replay extends the prompt with.

    def start_decode(self, rec: Dict[str, Any], max_new_tokens: int,
                     eos_token: Optional[int] = None,
                     timeout_s: float = 120.0) -> str:
        """Adopt a transfer and open a pull handle for it. The handle's
        first pulled token is the transfer's first token."""
        now = time.monotonic()
        stream = self._adopt(rec, max_new_tokens, eos_token, timeout_s)
        hid = f"{self.server_id}-h{next(_SERVER_SEQ)}"
        reaped: List[Any] = []
        with self._lock:
            self._streams[hid] = [stream, now]
            for k, (st, last) in list(self._streams.items()):
                if now - last > self._STREAM_REAP_S:
                    del self._streams[k]  # abandoned by a dead router
                    reaped.append(st)
        for st in reaped:
            # the abandoned request must not decode to completion —
            # same early-free as cancel_decode (KV + adapter pins drop
            # at the next tick boundary)
            self.engine.cancel_slot(st, "idle_reap")
        return hid

    def next_tokens(self, hid: str, max_tokens: int = 64,
                    wait_s: float = 2.0) -> Dict[str, Any]:
        """Pull up to `max_tokens` from an open handle: blocks up to
        `wait_s` for the FIRST token, then drains whatever is already
        produced. ``{"tokens": [...], "done": bool}`` — an empty pull
        with done=False is a keep-alive (the caller owns timeout and
        deadline policy)."""
        from ray_tpu.models.engine import _DONE

        with self._lock:
            entry = self._streams.get(hid)
            if entry is not None:
                entry[1] = time.monotonic()  # the idle-reap stamp
        if entry is None:
            raise KeyError(f"unknown decode stream {hid!r} "
                           f"(finished, cancelled, or reaped)")
        req = entry[0]._req
        toks: List[int] = []
        done = False
        try:
            tok = req.out.get(timeout=max(0.0, float(wait_s)))
            while True:
                if tok is _DONE:
                    done = True
                    break
                toks.append(int(tok))
                if len(toks) >= max(1, int(max_tokens)):
                    break
                tok = req.out.get_nowait()
        except queue.Empty:
            pass
        if toks:
            # counts the tokens AND consults chaos: a scripted
            # kill_replica at=token:K fires here, losing this pull's
            # reply — the mid-stream death the failover path replays
            self._count_decoded(len(toks))
        if done:
            with self._lock:
                self._streams.pop(hid, None)
            self.publish_telemetry()
            # per-request speculation accounting rides the final pull
            # so the router's decode_steady span can carry
            # accept/reject counts without an extra round trip
            return {"tokens": toks, "done": True,
                    "spec_proposed": int(getattr(req, "spec_proposed",
                                                 0)),
                    "spec_accepted": int(getattr(req, "spec_accepted",
                                                 0))}
        return {"tokens": toks, "done": done}

    def cancel_decode(self, hid: str,
                      reason: Optional[str] = None) -> bool:
        """Abandon a pull handle (router shed the request on deadline,
        failed it over, or PREEMPTED it for an interactive request):
        the engine CANCELS the slot — it frees, with its KV pins and
        adapter pin, at the next tick boundary instead of decoding the
        abandoned request to completion (the PR-12 known limit: those
        ticks were pure waste). The freed slot is immediately
        re-admittable. `reason` tags the engine's cancel accounting
        (``cancelled_by_reason``) so a preemption never reads as a
        deadline shed."""
        with self._lock:
            entry = self._streams.pop(hid, None)
        if entry is None:
            return False
        self.engine.cancel_slot(entry[0], reason)
        return True

    def _count_decoded(self, n: int) -> None:
        with self._lock:
            self._stats["decoded_tokens"] += n
        if self._chaos is not None:
            self._chaos.on_tokens(n)  # may os._exit (kill_replica)
        self.publish_telemetry()

    # -------------------------------------------------------- control plane

    def capacity(self) -> int:
        return self.engine.max_batch

    def free_slots(self) -> int:
        return self.engine.free_slots

    def prefill_programs(self) -> int:
        """`_prefill_paged` compile-cache size in THIS process — must
        stay flat on a pure decode replica (0 when it runs alone)."""
        from ray_tpu.models.engine import _prefill_paged

        try:
            return _prefill_paged._cache_size()
        except Exception:  # noqa: BLE001 — older jax without _cache_size
            return -1

    def describe(self) -> Dict[str, Any]:
        """Registration record for a router: identity, capacity, host
        (the decode-side placement-affinity anchor)."""
        return {"server_id": self.server_id, "role": "decode",
                "capacity": self.engine.max_batch,
                "machine": self.machine,
                "lora": self.lora_pool is not None}

    def publish_adapter(self, tenant: str,
                        adapter: Dict[str, Any]) -> int:
        """Local-source adapter publish (see PrefillServer twin)."""
        if self.lora_pool is None:
            raise ValueError("this decode server has no adapter pool")
        return int(self.lora_pool.source.publish(tenant, adapter))

    def refresh_adapter(self, tenant: str) -> bool:
        if self.lora_pool is None:
            return False
        return self.lora_pool.refresh(tenant)

    def reset_chaos_counts(self) -> bool:
        """Zero the chaos monkey's counters (see PrefillServer twin)."""
        if self._chaos is not None:
            self._chaos.reset_counts()
        return self._chaos is not None

    def prepare_for_shutdown(self, timeout_s: float = 30.0) -> bool:
        """Grace drain (the serve/replica.py shape, reused by autoscale
        scale-down): wait until every decode slot has finished its
        stream, then stop the engine. Returns whether the drain
        completed inside the window — the engine stops either way, so
        the caller may safely drop/kill the replica afterwards."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            drained = self.engine.free_slots == self.engine.max_batch
            if drained or time.monotonic() >= deadline:
                break
            time.sleep(0.05)
        self.stop()
        return drained

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
        s.update(role="decode", server_id=self.server_id,
                 capacity=self.engine.max_batch,
                 free_slots=self.engine.free_slots,
                 adopted=self.engine.adopted,
                 cancelled=self.engine.cancelled,
                 prefill_programs=self.prefill_programs())
        if self.engine.speculate_k:
            s["speculation"] = self.engine.speculation_stats()
        if self.lora_pool is not None:
            s["lora"] = self.lora_pool.stats()
        return s

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        _push_stats(self.server_id, self.stats())
        if self.lora_pool is not None:
            self.lora_pool.publish_telemetry(force=force)
        # the engine's own kvcache push carries the adoption counters
        # to the kvcache surface (per-phase truthfulness)
        self.engine.publish_kv_telemetry(force=True)

    def stop(self) -> None:
        self.engine.stop()
        self.publish_telemetry(force=True)


# ----------------------------------------------------------------- router

class _TierReplica:
    """One router-side replica slot. generate() holds the OBJECT (not an
    index) across its whole lifetime, so the replica set can grow,
    drain, and shrink mid-traffic without invalidating in-flight
    bookkeeping."""

    __slots__ = ("target", "rid", "cap", "inflight", "draining",
                 "machine", "lora")

    def __init__(self, target: Any, rid: str, cap: int,
                 machine: Optional[str] = None, lora: bool = False):
        self.target = target
        self.rid = rid
        self.cap = int(cap)
        self.inflight = 0
        self.draining = False
        self.machine = machine
        self.lora = bool(lora)

    def snapshot(self) -> Dict[str, Any]:
        return {"rid": self.rid, "target": self.target, "cap": self.cap,
                "inflight": self.inflight, "draining": self.draining,
                "machine": self.machine}


# cache-outcome weights for the router's recent hit-rate signal: a full
# hit skips the prefill entirely, a partial roughly halves it, a miss
# pays it all — the policy reads "fraction of prefill work the cache is
# absorbing right now"
_OUTCOME_WEIGHT = {"hit": 1.0, "partial": 0.5, "miss": 0.0}


class _PreemptSlot:
    """One PREEMPTIBLE in-flight request (priority class ``batch``,
    serve/qos.py) as the admission path sees it. Registered for the
    request's whole lifetime; ``cancel_fn`` is armed only while a
    decode stream is actually live (it cancels that stream's engine
    slot, reason-tagged ``preempt``). An interactive arrival that finds
    every decode slot taken picks the victim with the FEWEST delivered
    tokens — the cheapest replay — marks it ``preempted`` under the
    router lock, and fires the cancel outside it. The victim's pull
    loop notices (its stream ends early, or errors) and resumes
    through the SAME replay-with-history path as a replica-death
    failover: prompt+history re-prefills (a suffix-only prefill thanks
    to the prefix cache) and decode continues for the remaining
    budget, so the greedy bit-identity oracle covers preemption for
    free."""

    __slots__ = ("key", "tenant", "rep", "tokens", "preempted",
                 "cancel_fn")

    def __init__(self, key: int, tenant: Optional[str] = None):
        self.key = key
        self.tenant = tenant
        self.rep: Optional[_TierReplica] = None
        self.tokens = 0
        self.preempted = False
        self.cancel_fn: Optional[Callable[[], None]] = None


class DisaggRouter:
    """Dispatch + admission control over a prefill tier and a decode
    tier (each a sequence of in-process servers or actor handles).

    With an empty prefill tier the router degrades to the colocated
    single-engine path — same engine code, bit-identical outputs — so
    one deployment shape serves both modes.

    The replica sets are LIVE: ``add_prefill``/``add_decode`` admit a
    new replica to dispatch immediately, ``begin_drain`` stops
    dispatching to one while its in-flight requests finish and its KV
    transfers get acked, and ``remove`` retires it once ``drained`` —
    the serve/autoscale.py control loop drives exactly this API
    mid-traffic. Dispatch policy: decode by free-slot count, prefill by
    prefix-cache affinity WITHIN the subset co-located with the chosen
    decode replica's host (when one exists), so KV transfers stay on
    shm — the ``shm_affinity`` split in stats() reports how often that
    held."""

    def __init__(self, decode: Sequence[Any] = (),
                 prefill: Sequence[Any] = (), *,
                 colocated: Any = None,
                 max_queue_depth: Optional[int] = None,
                 retry_after_s: Optional[float] = None,
                 affinity_tokens: int = 16,
                 failover_attempts: Optional[int] = None,
                 failover_wait_s: float = 15.0,
                 stream_chunk_tokens: int = 32,
                 router_id: Optional[str] = None):
        # every combination generate() cannot serve is rejected HERE,
        # not per-request after a prefill was already published
        if prefill and not decode:
            raise ValueError(
                "a prefill tier needs a decode tier to stream KV to")
        if not prefill and colocated is None:
            raise ValueError(
                "need a prefill+decode pair or a colocated engine")
        self._colocated = colocated
        # the deployment SHAPE is fixed at construction: a disagg
        # router whose whole prefill tier momentarily died waits for
        # the self-healer's replacement (it never falls through to a
        # colocated engine it may not have)
        self._disagg_mode = bool(prefill)
        if max_queue_depth is None:
            max_queue_depth = int(os.environ.get(
                "RAY_TPU_DISAGG_QUEUE_DEPTH", "8"))
        self.max_queue_depth = max(0, int(max_queue_depth))
        if retry_after_s is None:
            retry_after_s = float(os.environ.get(
                "RAY_TPU_DISAGG_RETRY_AFTER_S", "1.0"))
        self.retry_after_s = float(retry_after_s)
        # bounded failover budget: EXTRA attempts after the first (so
        # the default survives any single replica failure with one
        # retry to spare); exhaustion sheds with cause "failover"
        if failover_attempts is None:
            failover_attempts = int(os.environ.get(
                "RAY_TPU_FAILOVER_ATTEMPTS", "2"))
        self.failover_attempts = max(0, int(failover_attempts))
        # how long a failed-over request waits for a survivor (or a
        # self-healer replacement) when a whole tier momentarily has
        # zero live replicas
        self.failover_wait_s = max(0.0, float(failover_wait_s))
        self.stream_chunk_tokens = max(1, int(stream_chunk_tokens))
        # prompts sharing their first `affinity_tokens` tokens (the
        # system prompt's first cache block) land on one prefill replica
        self.affinity_tokens = max(1, int(affinity_tokens))
        self.router_id = router_id or \
            f"router-{os.getpid()}-{next(_SERVER_SEQ)}"
        self._lock = threading.Lock()
        # global KV plane (serve/kvplane.py): prefer the replica that
        # HAS the prefix (conductor directory) over the one the hash
        # says probably does; block size learned from prefill describe()
        from .kvplane import directory_enabled as _kvp_dir_enabled
        self._kvplane_dir = _kvp_dir_enabled()
        self._kv_block_size: Optional[int] = None
        self._decode: List[_TierReplica] = [
            self._register(d, "decode") for d in decode]
        self._prefill: List[_TierReplica] = [
            self._register(p, "prefill") for p in prefill]
        if not self._decode:
            self._decode = [_TierReplica(
                colocated, f"{self.router_id}-colocated",
                int(colocated.max_batch))]
        self._push_retention_hint()
        # recent-signal windows (serve/autoscale.SlidingWindow): the
        # policy — and `recent` in stats() — reads these, not lifetime
        # counters, so a load shift shows up within the window
        self._ttft_win = SlidingWindow()
        self._depth_win = SlidingWindow()
        self._pf_inflight_win = SlidingWindow()
        self._cache_win = SlidingWindow()
        self._pf_inflight = 0
        self._stats = {k: 0 for k in (
            "dispatched", "completed", "shed", "max_pending",
            "shm_affinity_hits", "shm_affinity_total",
            "tenant_affinity_hits", "tenant_affinity_total",
            "tier_wakeups", "preemptions", "preempted_requests",
            "directory_hits", "directory_misses",
            "directory_fallbacks")}
        # QoS preemption (serve/qos.py classes): batch-class requests
        # register here while in flight; an interactive arrival that
        # finds every slot taken cancels the cheapest one and rides
        # its freed slot — the victim resumes via the failover replay
        self._preempt_seq = itertools.count()
        self._preempt_reg: Dict[int, _PreemptSlot] = {}
        # scale-from-zero hook (serve/autoscale.py): called with the
        # tier name when an arrival finds that tier EMPTY — the
        # autoscaler's waker spawns a replica through the tier factory
        # outside hysteresis, and the arrival waits for it
        self._tier_waker: Optional[Callable[[str], None]] = None
        # multi-tenant LoRA (serve/lora.py): per-tenant shed/SLO/
        # latency isolation — one tenant's overload or failure must
        # never read as another's. LRU-capped so a tenant sweep can't
        # grow the router without bound; the SLO line is the same
        # TTFT target the autoscale policy chases.
        self._tenant_stats: "OrderedDict[str, Dict[str, Any]]" = \
            OrderedDict()
        self._tenant_decode: "OrderedDict[str, str]" = OrderedDict()
        self._tenant_cap = 512
        self._tenant_slo_ms = default_target_p99_ms()
        # serving-fault-tolerance accounting (the servefault surface):
        # failover attempts per phase, requests that survived >= 1
        # failover, sheds by attributed cause, corpses removed
        self._sf = {
            "failovers": {"prefill": 0, "decode": 0},
            "failover_requests": 0,
            "sheds_by_cause": {},
            "removed_dead": {"prefill": 0, "decode": 0},
        }
        # recovery cost of each failover: ms from failure detection to
        # the resumed stream's re-prefill landing (the chaos benchmark
        # reports this window's summary as the recovery impact)
        self._failover_win = SlidingWindow()
        self._last_push = 0.0
        self._last_sf_push = 0.0
        disagg_metrics()
        servefault_metrics()

    # ----------------------------------------------------- replica set ops

    def _register(self, target: Any, tier: str) -> _TierReplica:
        try:
            info = _call(target, "describe")
        except Exception:  # noqa: BLE001 — pre-describe replica impls
            info = {}
        rid = info.get("server_id") or \
            f"{tier}-{self.router_id}-{next(_SERVER_SEQ)}"
        cap = int(info.get("capacity")
                  or (_call(target, "capacity") if tier == "decode"
                      else 0))
        if tier == "prefill" and self._kv_block_size is None:
            bs = info.get("kv_block_size")
            if bs:
                self._kv_block_size = int(bs)
        return _TierReplica(target, rid, cap, info.get("machine"),
                            bool(info.get("lora")))

    def _push_retention_hint(self) -> None:
        """Every admissible request can be in flight at once and
        affinity can route ALL of them to one prefill server — push the
        bound so its retention window can never reap a transfer a
        decode replica is about to fetch. Re-pushed whenever the
        replica set grows."""
        with self._lock:
            prefill = list(self._prefill)
            hint = 2 * sum(r.cap + self.max_queue_depth
                           for r in self._decode)
        for pf in prefill:
            try:
                # best-effort hint, supervised by the except below
                _call(pf.target, "set_retention", hint, block=False)  # shardlint: disable=unsupervised-actor-call
            except Exception:  # noqa: BLE001 — replica mid-restart
                pass

    def add_decode(self, target: Any) -> str:
        """Admit a new decode replica — it becomes dispatchable the
        moment this returns."""
        rep = self._register(target, "decode")
        with self._lock:
            self._decode.append(rep)
        self._push_retention_hint()
        self.publish_telemetry(force=True)
        return rep.rid

    def add_prefill(self, target: Any) -> str:
        """Admit a new prefill replica (affinity re-hashes over the
        grown set on the next dispatch)."""
        rep = self._register(target, "prefill")
        with self._lock:
            self._prefill.append(rep)
        self._push_retention_hint()
        self.publish_telemetry(force=True)
        return rep.rid

    def set_tier_waker(self,
                       fn: Optional[Callable[[str], bool]]) -> None:
        """Attach the scale-from-zero hook (serve/autoscale.py): called
        with the tier name ("prefill"|"decode") when a request arrives
        to an EMPTY tier; returns whether a wake was actually initiated
        (only a min_replicas=0 tier wakes — for any other tier the
        arrival must keep the pre-existing behavior: shed immediately
        on decode, or wait for the self-healer on prefill). Must be
        non-blocking — the waker spawns its replica off-thread while
        the arrival waits."""
        self._tier_waker = fn

    def _wake_tier(self, tier: str) -> bool:
        """Fire the waker; True only when it reports a wake is coming —
        the caller's cue to wait for the replica instead of shedding.
        Bookkeeping (counter + event) only on actual wakes."""
        waker = self._tier_waker
        if waker is None:
            return False
        try:
            woke = bool(waker(tier))
        except Exception:  # noqa: BLE001 — treat as no wake coming
            return False
        if woke:
            with self._lock:
                self._stats["tier_wakeups"] += 1
            _notify_event({"kind": "tier_wake",
                           "router": self.router_id, "tier": tier})
        return woke

    def _lora_enabled(self) -> bool:
        """Whether this deployment can serve tenant-tagged requests:
        any tier replica advertised an adapter pool (describe()'s
        `lora` field), or the colocated engine holds one. Live — a
        LoRA-enabled replica added mid-traffic enables the tenant
        default from then on."""
        if self._colocated is not None and \
                getattr(self._colocated, "lora_pool", None) is not None:
            return True
        with self._lock:
            return any(r.lora for r in self._prefill + self._decode)

    def _tier(self, tier: str) -> List[_TierReplica]:
        if tier not in ("prefill", "decode"):
            raise ValueError(f"unknown tier {tier!r}")
        return self._prefill if tier == "prefill" else self._decode

    def tier_replicas(self, tier: str) -> List[Dict[str, Any]]:
        with self._lock:
            return [r.snapshot() for r in self._tier(tier)]

    def begin_drain(self, tier: str, rid: str, *,
                    allow_empty: bool = False) -> bool:
        """Stop dispatching to one replica; its in-flight requests keep
        running and its KV transfers still get acked. Refuses to drain
        the LAST active replica of a tier (the router must stay able
        to serve) unless ``allow_empty`` — the scale-to-zero path,
        where the attached tier waker makes an empty tier serveable
        again on the next arrival. Returns whether the drain
        started."""
        with self._lock:
            reps = self._tier(tier)
            active = [r for r in reps if not r.draining]
            for r in reps:
                if r.rid == rid and not r.draining:
                    if len(active) <= 1 and not (
                            allow_empty and self._tier_waker is not None):
                        return False
                    r.draining = True
                    break
            else:
                return False
        self.publish_telemetry(force=True)
        return True

    def drained(self, tier: str, rid: str) -> bool:
        """True when a draining replica has zero in-flight left (its
        dispatch stopped at begin_drain; this is the router-side half of
        the grace drain — the replica-side prepare_for_shutdown
        double-checks engine slots and unacked transfers)."""
        with self._lock:
            for r in self._tier(tier):
                if r.rid == rid:
                    return r.draining and r.inflight == 0
        return True  # already removed

    def remove(self, tier: str, rid: str) -> Optional[Any]:
        """Retire a draining replica from the set; returns its target
        so the caller can tear it down (grace-drain first — see
        serve/autoscale.py)."""
        with self._lock:
            reps = self._tier(tier)
            for i, r in enumerate(reps):
                if r.rid == rid:
                    if not r.draining:
                        raise ValueError(
                            f"{tier} replica {rid} is not draining — "
                            "begin_drain() first so dispatch stops "
                            "before the replica disappears")
                    del reps[i]
                    return r.target
        return None

    def remove_dead(self, tier: str, rid: str) -> bool:
        """Remove a DEAD replica immediately — distinct from the drain
        flow: no grace, no draining precondition (a corpse mid-drain is
        reaped too), its in-flight requests have already failed over or
        are about to. Called by the failover wrapper on an observed
        death and by the serve/autoscale.py self-healer on an
        actor-death event. Idempotent."""
        with self._lock:
            reps = self._tier(tier)
            for i, r in enumerate(reps):
                if r.rid == rid:
                    del reps[i]
                    self._sf["removed_dead"][tier] += 1
                    break
            else:
                return False
        self.publish_telemetry(force=True)
        self.publish_servefault(force=True)
        return True

    # ------------------------------------------------------- failover core

    def _tier_call(self, rep: _TierReplica, tier: str, method: str,
                   *args, block: bool = True, **kw):
        """THE supervised path for data-plane calls on a tier replica
        (shardlint's unsupervised-actor-call rule flags bare calls that
        bypass it): a death-shaped failure removes the corpse from the
        replica set, emits the failover markers, and re-raises as
        ReplicaDeadError so generate()'s bounded retry can re-route."""
        try:
            return _call(rep.target, method, *args, block=block, **kw)
        except _DEATH_TYPES as e:
            self.remove_dead(tier, rep.rid)
            raise ReplicaDeadError(tier, rep.rid, e) from e

    def _count_failover(self, phase: str, rid: str, attempt: int,
                        detail: str) -> None:
        with self._lock:
            self._sf["failovers"][phase] += 1
        servefault_metrics()["failovers"].inc(tags={"phase": phase})
        _notify_resilience({"kind": "failover", "phase": phase,
                            "router": self.router_id, "replica": rid,
                            "attempt": attempt, "detail": detail[:200]})
        self.publish_servefault()

    def _tenant_rec_locked(self, tenant: str) -> Dict[str, Any]:
        rec = self._tenant_stats.get(tenant)
        if rec is None:
            rec = {"dispatched": 0, "completed": 0, "shed": 0,
                   "sheds_by_cause": {}, "slo_misses": 0,
                   "ttft": SlidingWindow(), "latency": SlidingWindow()}
            self._tenant_stats[tenant] = rec
            while len(self._tenant_stats) > self._tenant_cap:
                self._tenant_stats.popitem(last=False)
        self._tenant_stats.move_to_end(tenant)
        return rec

    def _shed(self, cause: str, message: str,
              tenant: Optional[str] = None) -> RequestShedError:
        """Count + build an attributed shed (the caller raises it):
        every shed path reports the same one set of numbers. `tenant`
        charges the shed to that tenant's isolated counters too."""
        with self._lock:
            self._stats["shed"] += 1
            by = self._sf["sheds_by_cause"]
            by[cause] = by.get(cause, 0) + 1
            if tenant is not None:
                trec = self._tenant_rec_locked(tenant)
                trec["shed"] += 1
                tby = trec["sheds_by_cause"]
                tby[cause] = tby.get(cause, 0) + 1
        shed_counter().inc(tags={"app": "disagg",
                                 "deployment": self.router_id})
        servefault_metrics()["sheds"].inc(tags={"cause": cause})
        _notify_event({"kind": "shed", "router": self.router_id,
                       "cause": cause,
                       "retry_after_s": self.retry_after_s})
        self.publish_telemetry()
        self.publish_servefault()
        return RequestShedError(message, retry_after_s=self.retry_after_s,
                                cause=cause)

    # ------------------------------------------------------------ admission

    def _admit_or_shed(self, tenant: Optional[str] = None,
                       deadline: Optional[float] = None,
                       priority: Optional[str] = None) -> _TierReplica:
        """Reserve a decode replica or shed. Sheds when EVERY active
        replica's in-flight estimate has reached capacity +
        max_queue_depth — the bound that keeps queue depth finite
        (draining replicas receive nothing, so they neither admit nor
        extend the bound). The bound check and the in-flight
        reservation happen under ONE lock acquisition (check-then-act
        would let N racing callers all pass the check before any
        reserves, exceeding the bound by N-1); shed-side metrics and
        the conductor notify run after release so overload never
        serializes healthy admissions behind a socket write.

        `tenant` adds TENANT-AFFINITY beside the load policy: the
        replica that served this tenant last already holds its adapter
        resident (serve/lora.py pool), so it is preferred while it has
        admission headroom — a cross-replica spray would page the same
        adapter into every pool.

        Scale-from-zero (serve/autoscale.py min_replicas=0): when the
        decode tier is EMPTY (drained to zero, not merely full) and a
        tier waker is attached, the FIRST arrival is the scale-up
        signal — the waker spawns a replica through the tier factory
        and this admission waits up to ``failover_wait_s`` for it to
        register instead of shedding. A full-but-live tier still sheds
        immediately (that is load, not absence).

        `priority` (serve/qos.py classes): an ``interactive`` arrival
        that finds every replica full PREEMPTS the cheapest registered
        batch-class request instead of shedding — it rides the
        victim's replica (deliberately one reservation past the bound:
        the parked victim keeps its own reservation while it waits to
        resume, so nothing leaks when both complete). The victim
        resumes through the failover replay, bit-identical."""
        affinity_hit = False
        wake_until: Optional[float] = None
        while True:
            victim: Optional[_PreemptSlot] = None
            with self._lock:
                open_reps = [r for r in self._decode if not r.draining
                             and r.inflight < r.cap
                             + self.max_queue_depth]
                pending = sum(r.inflight for r in self._decode)
                if not open_reps and priority == "interactive":
                    victim = self._pick_victim_locked()
                    if victim is not None:
                        rep = victim.rep
                        rep.inflight += 1
                        pending += 1
                        self._stats["dispatched"] += 1
                        self._stats["preemptions"] += 1
                        self._stats["max_pending"] = max(
                            self._stats["max_pending"], pending)
                        if tenant is not None:
                            self._tenant_rec_locked(
                                tenant)["dispatched"] += 1
                if open_reps:
                    # probe-free first cut: least estimated in-flight,
                    # reserved NOW so the bound holds under concurrency
                    rep = min(open_reps, key=lambda r: r.inflight)
                    if tenant is not None:
                        self._stats["tenant_affinity_total"] += 1
                        want = self._tenant_decode.get(tenant)
                        for r in open_reps:
                            if r.rid == want:
                                rep = r
                                affinity_hit = True
                                self._stats["tenant_affinity_hits"] += 1
                                break
                        self._tenant_rec_locked(
                            tenant)["dispatched"] += 1
                    rep.inflight += 1
                    pending += 1
                    self._stats["dispatched"] += 1
                    self._stats["max_pending"] = max(
                        self._stats["max_pending"], pending)
                tier_empty = not any(not r.draining
                                     for r in self._decode)
            if victim is not None:
                # cancel fires OUTSIDE the lock (it's an RPC); the
                # probe refinement below is naturally skipped — the
                # preemptor must ride exactly the slot it just freed
                self._fire_preemption(victim)
                self._depth_win.add(pending)
                break
            if open_reps:
                self._depth_win.add(pending)
                break
            if tier_empty and self._tier_waker is not None:
                # one wake attempt per admission; the wait engages only
                # when the waker reports a replica is actually coming
                # (min_replicas=0 tier) — a dead min_replicas>=1 tier
                # keeps the pre-existing immediate shed
                if wake_until is None and self._wake_tier("decode"):
                    wake_until = time.monotonic() + self.failover_wait_s
                if wake_until is not None \
                        and time.monotonic() < wake_until:
                    self._check_deadline(deadline, tenant)
                    time.sleep(0.1)
                    continue
            self._depth_win.add(pending)
            # _shed pushes the snapshot NOW (0.5s-throttled): under
            # sustained overload nothing completes, and a completion-
            # only push would freeze the conductor surfaces — queue
            # depth aging out to 0 — during exactly the storm they
            # exist to show
            raise self._shed(
                "capacity",
                f"disagg router {self.router_id}: every decode "
                f"replica is at capacity + queue depth "
                f"{self.max_queue_depth} (pending {pending}); retry "
                f"after {self.retry_after_s:.1f}s", tenant)
        if self._prefill and len(open_reps) > 1 and not affinity_hit:
            # refine by live free-slot count (the decode-pick policy);
            # the in-flight estimate breaks ties and covers probe lag.
            # The probes are ISSUED before any is awaited so N actor
            # replicas answer concurrently — sequential blocking gets
            # here would add N x RPC latency to every dispatch.
            # Moving the reservation re-checks the target's bound under
            # the lock — a refinement may not overfill a replica that
            # filled up while we probed.
            try:
                from ray_tpu._private.object_store import ObjectRef

                import ray_tpu

                # read-only probe, supervised by the except below
                probes = [(r, _call(r.target, "free_slots",  # shardlint: disable=unsupervised-actor-call
                                    block=False)) for r in open_reps]
                # expected free slots once in-transit dispatches land:
                # the probe already excludes EXECUTING requests, which
                # are also in this router's in-flight estimate, so
                # subtracting the full estimate would double-count them
                # and rank a deep backlog above a busy-but-shallower
                # replica. cap - inflight is that expectation for load
                # this router dispatched; min() with the probe keeps it
                # honest about slots held by load we never saw.
                frees = [(min(int(ray_tpu.get(v)
                                  if isinstance(v, ObjectRef) else v),
                              r.cap - r.inflight), i)
                         for i, (r, v) in enumerate(probes)]
                best = probes[max(frees)[1]][0]
            except Exception:  # noqa: BLE001 — replica mid-restart
                best = rep
            if best is not rep:
                with self._lock:
                    if not best.draining and best.inflight < \
                            best.cap + self.max_queue_depth:
                        rep.inflight -= 1
                        best.inflight += 1
                        rep = best
        if tenant is not None:
            # record the replica that will ACTUALLY serve (after the
            # probe refinement above) — it's the one paging the
            # tenant's adapter, so it's the one affinity must point at
            self._note_tenant_decode(tenant, rep.rid)
        disagg_metrics()["queue_depth"].set(
            pending, tags={"router": self.router_id})
        self.publish_telemetry()
        return rep

    # ----------------------------------------------------- qos preemption

    def _preempt_register(self, priority: Optional[str],
                          tenant: Optional[str]
                          ) -> Optional[_PreemptSlot]:
        """Make a batch-class request visible to interactive
        admission. Non-batch (and unclassified) requests return None —
        they are never preemption victims."""
        if priority != "batch":
            return None
        slot = _PreemptSlot(next(self._preempt_seq), tenant)
        with self._lock:
            self._preempt_reg[slot.key] = slot
        return slot

    def _preempt_unregister(self, slot: Optional[_PreemptSlot]) -> None:
        if slot is None:
            return
        with self._lock:
            self._preempt_reg.pop(slot.key, None)

    def _pick_victim_locked(self) -> Optional[_PreemptSlot]:
        """Cheapest-replay victim: the live batch stream with the
        fewest delivered tokens (its replay re-prefills the least
        history). Caller holds the router lock; marking ``preempted``
        here makes the pick exactly-once under racing interactive
        arrivals."""
        cands = [s for s in self._preempt_reg.values()
                 if s.cancel_fn is not None and s.rep is not None
                 and not s.preempted]
        if not cands:
            return None
        victim = min(cands, key=lambda s: s.tokens)
        victim.preempted = True
        return victim

    def _fire_preemption(self, victim: _PreemptSlot) -> None:
        """Cancel the victim's live decode stream (reason-tagged
        ``preempt`` down in the engine) and count the preemption into
        the gateway surface. The victim's pull loop notices its stream
        ending early and resumes via replay-with-history; its
        reservation never moves, so slot accounting stays balanced
        when both requests complete."""
        fn = victim.cancel_fn
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — victim mid-teardown
                pass
        try:
            from .qos import gateway_metrics, push_gateway_event

            gateway_metrics()["preemptions"].inc()
            push_gateway_event({"kind": "preempt",
                                "router": self.router_id,
                                "victim_tenant": victim.tenant,
                                "tokens_done": victim.tokens})
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        _notify_event({"kind": "preempt", "router": self.router_id,
                       "victim_tenant": victim.tenant})
        self.publish_telemetry()

    def _check_abort(self, deadline: Optional[float],
                     tenant: Optional[str] = None,
                     cancel_event: Any = None) -> None:
        """_check_deadline plus the gateway's client-disconnect
        signal: a set cancel_event sheds with cause ``disconnect`` —
        an abandoned decode must stop burning ticks for a socket
        nobody reads."""
        self._check_deadline(deadline, tenant)
        if cancel_event is not None and cancel_event.is_set():
            raise self._shed(
                "disconnect",
                f"disagg router {self.router_id}: client disconnected "
                f"mid-request; decode cancelled", tenant)

    def _note_tenant_decode(self, tenant: str, rid: str) -> None:
        with self._lock:
            self._tenant_decode[tenant] = rid
            self._tenant_decode.move_to_end(tenant)
            while len(self._tenant_decode) > self._tenant_cap:
                self._tenant_decode.popitem(last=False)

    def _complete(self, rep: _TierReplica, ok: bool = True, *,
                  tenant: Optional[str] = None,
                  wall_ms: Optional[float] = None) -> None:
        """Release a request's reservation; `completed` counts only
        requests that RETURNED tokens — a shed-after-admission
        (deadline, failover exhaustion) or an error releases the slot
        without counting, so completed + shed + errors reconciles with
        dispatched instead of double-counting the shed ones."""
        with self._lock:
            if rep.inflight > 0:
                rep.inflight -= 1
            if ok:
                self._stats["completed"] += 1
                if tenant is not None:
                    trec = self._tenant_rec_locked(tenant)
                    trec["completed"] += 1
                    if wall_ms is not None:
                        trec["latency"].add(wall_ms)
            pending = sum(r.inflight for r in self._decode)
        disagg_metrics()["queue_depth"].set(
            pending, tags={"router": self.router_id})
        self.publish_telemetry()

    # ------------------------------------------------------------- dispatch

    def _directory_entry(self, prompt: np.ndarray,
                         tenant: Optional[str]
                         ) -> Optional[Dict[str, Any]]:
        """Ask the conductor's KV-plane prefix directory who HOLDS this
        prompt's longest published prefix. Returns None when the lookup
        was not attempted (directory off, no cluster, block size not
        yet learned from a prefill replica, or a tenant-tagged request
        — the tenant namespace folds in the adapter VERSION, which only
        the replica's adapter pool knows) and ``{}`` when it ran and
        found nothing; any entry is advisory — a miss always falls back
        to the affinity hash, bit-identically."""
        if not self._kvplane_dir or tenant is not None:
            return None
        bs = self._kv_block_size
        w = _worker()
        if bs is None or w is None:
            return None
        from .kvplane import directory_lookup
        try:
            # namespace None, not "": the digest chain must be rooted
            # exactly like the replicas' default-namespace index (the
            # conductor-side directory key maps None -> "" itself)
            entry = directory_lookup(w, None, [int(t) for t in prompt],
                                     bs)
        except Exception:  # noqa: BLE001 — conductor unreachable
            return None
        return entry if entry is not None else {}

    def _pick_prefill(self, prompt: np.ndarray,
                      decode_machine: Optional[str],
                      tenant: Optional[str] = None
                      ) -> Tuple[_TierReplica,
                                 Optional[Dict[str, Any]]]:
        """Prefix-cache affinity WITHIN the host-local subset: among
        prefill replicas co-located with the chosen decode replica (so
        the KV transfer rides shm, never RPC), the prompt's first cache
        block hashes to one stable choice; with no co-located replica
        the hash falls back to the whole active set. On one host the
        subset IS the whole set, so single-host affinity (and
        bit-identity) is unchanged. The TENANT joins the hash beside
        the prompt head: a tenant's prompts land on the replica that
        already holds its adapter (and its namespace-keyed KV) — the
        tenant-affinity half of the multi-tenant routing policy.

        With the global KV plane on, the conductor's prefix directory
        upgrades the hash from "who PROBABLY has it" to "who HAS it":
        a live holder wins outright; a holder that has left the pool
        degrades to the hash plus a tier-3 hint the chosen replica can
        fetch through the transfer plane. Returns ``(replica, hint)``
        where hint is None except on that fallback path."""
        dir_entry = self._directory_entry(prompt, tenant)
        head = (tenant,) + tuple(
            int(t) for t in prompt[:self.affinity_tokens])
        hint: Optional[Dict[str, Any]] = None
        outcome: Optional[str] = None
        with self._lock:
            cands = [r for r in self._prefill if not r.draining]
            if not cands:  # every prefill draining: keep serving
                cands = list(self._prefill)
            if not cands:  # every prefill DEAD: caller waits/sheds
                raise LookupError("no live prefill replica")
            rep = None
            if dir_entry:
                holder = dir_entry.get("holder")
                by_rid = {r.rid: r for r in cands}
                if holder in by_rid:
                    rep = by_rid[holder]
                    outcome = "hit"
                    self._stats["directory_hits"] += 1
                else:
                    # entry survives its holder (death, drain): route
                    # by hash but hand the replica the tier-3 pointer
                    hint = dir_entry
                    outcome = "fallback"
                    self._stats["directory_fallbacks"] += 1
            elif dir_entry is not None:  # lookup ran, found nothing
                outcome = "miss"
                self._stats["directory_misses"] += 1
            if rep is None:
                local = [r for r in cands
                         if decode_machine is not None
                         and r.machine == decode_machine]
                pool = local or cands
                rep = pool[hash(head) % len(pool)]
            self._stats["shm_affinity_total"] += 1
            if decode_machine is not None \
                    and rep.machine == decode_machine:
                self._stats["shm_affinity_hits"] += 1
        if outcome is not None:
            from .kvplane import kvplane_metrics
            kvplane_metrics()["directory"].inc(
                tags={"outcome": outcome})
            if outcome == "hit":
                _notify_kvplane({
                    "kind": "directory_hit", "router": self.router_id,
                    "replica": rep.rid,
                    "digest": dir_entry.get("digest"),
                    "blocks": dir_entry.get("blocks")})
        return rep, hint

    def _check_deadline(self, deadline: Optional[float],
                        tenant: Optional[str] = None) -> None:
        """Shed with cause `deadline` the moment the request outlives
        its budget — it must never occupy a decode slot (or a failover
        attempt) past it."""
        if deadline is not None and time.perf_counter() > deadline:
            raise self._shed(
                "deadline",
                f"disagg router {self.router_id}: request outlived its "
                f"deadline; retry after {self.retry_after_s:.1f}s",
                tenant)

    def _ack_transfer(self, pf: _TierReplica, rec: Dict[str, Any]
                      ) -> None:
        """Release the sender's chunk refs, consumed or abandoned: an
        un-acked record pins them until the retention window overflows
        — which on a quiet tier is never. The prefill replica may
        itself be dead by now; then its refs died with it."""
        try:
            # fire-and-forget on a possibly-dead replica — failure here
            # must not consume a failover attempt
            _call(pf.target, "ack", rec["transfer_id"], block=False)  # shardlint: disable=unsupervised-actor-call
        except Exception:  # noqa: BLE001 — replica already dead
            pass

    def _shed_pool_exhausted(self, phase: str,
                             tenant: Optional[str],
                             e: BaseException) -> RequestShedError:
        """The one adapter-pool-exhausted shed (colocated submit,
        prefill, and decode paths all raise through here): a CAPACITY
        condition, attributed to the tenant, never a failover."""
        return self._shed(
            "capacity",
            f"disagg router {self.router_id}: {phase} adapter pool "
            f"exhausted (every row pinned); retry after "
            f"{self.retry_after_s:.1f}s", tenant)

    def _check_request_fault(self, tenant: Optional[str],
                             e: BaseException) -> None:
        """Classify a data-plane failure that is NOT a replica death:
        tenant-configuration errors re-raise to the caller (retrying
        reproduces them; shedding would mislabel a client mistake as a
        serving fault), everything else returns so the bounded
        failover budget applies."""
        if _is_lora_config_error(e):
            raise ValueError(
                f"tenant {tenant!r} is misconfigured for this "
                f"deployment: {str(e)[:240]}") from e

    def _attempt_failed(self, phase: str, rid: str, attempt: int,
                        err: BaseException,
                        tenant: Optional[str] = None) -> None:
        """Account one failed attempt; sheds with cause `failover` when
        the bounded budget is exhausted."""
        self._count_failover(phase, rid, attempt,
                             f"{type(err).__name__}: {err}")
        if attempt > self.failover_attempts:
            raise self._shed(
                "failover",
                f"disagg router {self.router_id}: {phase} failure on "
                f"attempt {attempt}/{1 + self.failover_attempts} "
                f"({type(err).__name__}: {str(err)[:160]}); failover "
                f"budget exhausted", tenant) from err

    def _pick_prefill_or_wait(self, prompt: np.ndarray,
                              decode_machine: Optional[str],
                              deadline: Optional[float],
                              tenant: Optional[str] = None
                              ) -> Tuple[_TierReplica,
                                         Optional[Dict[str, Any]]]:
        """_pick_prefill, waiting out a momentarily-empty tier (every
        prefill replica dead — self-healer replacement in flight — or
        drained to zero: the first LookupError fires the scale-from-
        zero waker) up to ``failover_wait_s`` before shedding with
        cause failover."""
        wait_until = time.monotonic() + self.failover_wait_s
        woke = False
        while True:
            try:
                return self._pick_prefill(prompt, decode_machine,
                                          tenant)
            except LookupError:
                if not woke:
                    self._wake_tier("prefill")
                    woke = True
            self._check_deadline(deadline, tenant)
            if time.monotonic() >= wait_until:
                raise self._shed(
                    "failover",
                    f"disagg router {self.router_id}: no live prefill "
                    f"replica after {self.failover_wait_s:.0f}s",
                    tenant)
            time.sleep(0.25)

    def _reserve_survivor(self, old: _TierReplica,
                          deadline: Optional[float],
                          tenant: Optional[str] = None
                          ) -> _TierReplica:
        """Move an ACCEPTED request's reservation off a failed decode
        replica onto a survivor. Failover never re-runs admission —
        the request was accepted and the dead replica's slot vanished
        with it — so the survivor is chosen by least in-flight without
        re-checking the shed bound. Waits out a momentarily-empty tier
        (self-healer replacement in flight) like the prefill twin. The
        swap is atomic under the lock: `old` keeps its reservation
        until the survivor holds one, so the caller's release-on-exit
        always has exactly one reservation to release."""
        wait_until = time.monotonic() + self.failover_wait_s
        while True:
            with self._lock:
                cands = [r for r in self._decode if not r.draining]
                if cands:
                    rep = min(cands, key=lambda r: r.inflight)
                    rep.inflight += 1
                    if old.inflight > 0:
                        old.inflight -= 1
                else:
                    rep = None
            if rep is not None:
                if tenant is not None:
                    # failover moved the request (and its adapter
                    # page-in) to the survivor — affinity follows
                    self._note_tenant_decode(tenant, rep.rid)
                return rep
            self._check_deadline(deadline, tenant)
            if time.monotonic() >= wait_until:
                raise self._shed(
                    "failover",
                    f"disagg router {self.router_id}: no live decode "
                    f"replica after {self.failover_wait_s:.0f}s",
                    tenant)
            time.sleep(0.25)

    def generate(self, prompt_tokens, max_new_tokens: int,
                 eos_token: Optional[int] = None, *,
                 timeout_s: float = 120.0,
                 deadline_s: Optional[float] = None,
                 on_first_token=None,
                 token_sleep_s: float = 0.0,
                 tenant: Optional[str] = None,
                 priority: Optional[str] = None,
                 on_tokens=None,
                 cancel_event: Any = None) -> List[int]:
        """One request end-to-end. `on_first_token()` (optional) fires
        the moment the first token exists — at prefill completion under
        disaggregation — which is what the harness's TTFT measures.
        `token_sleep_s` simulates a slow client consuming the stream
        (bench_serve.py's backpressure knob): decode ticks must keep
        serving OTHER requests while this one drains slowly.
        `deadline_s` bounds the request's total wall time — past it the
        request sheds with cause ``deadline`` instead of occupying a
        slot forever.

        `tenant` (multi-tenant LoRA, serve/lora.py): serve the request
        under that tenant's adapter — tenant-affinity placement,
        (tenant, prompt)-keyed prefix cache, per-tenant shed/SLO/
        latency counters. Defaults to the current serve request's
        multiplexed-model-id (serve/multiplex.py), so a multiplexed
        deployment is tenant-tagged with no extra plumbing.

        The failover invariant: once this method ADMITS a request, it
        either returns the complete token list — bit-identical to an
        uninterrupted greedy run, surviving any single tier-replica
        death via bounded replay — or raises a RequestShedError with an
        attributed cause. It never silently drops.

        QoS (serve/qos.py, the HTTP front door): `priority` names the
        request's class — ``"batch"`` registers it as a preemption
        victim candidate, ``"interactive"`` lets it preempt a batch
        stream when every slot is taken (the victim resumes via the
        failover replay, bit-identical; the preemptor rides the freed
        slot). `on_tokens(list)` streams each delivered chunk to the
        caller as it lands (the gateway's SSE bridge). `cancel_event`
        (a threading.Event) aborts the request with shed cause
        ``disconnect`` when set — the gateway sets it when the HTTP
        client goes away. All three default to None: in-process
        callers are byte-for-byte unaffected."""
        if priority is not None and priority not in ("interactive",
                                                     "batch"):
            raise ValueError(
                f"unknown priority class {priority!r}; expected "
                f"'interactive' or 'batch'")
        if tenant is None and self._lora_enabled():
            # the implicit multiplexed-model-id default applies ONLY to
            # LoRA-enabled deployments: a plain multiplexed deployment
            # routing through a pool-less router must keep working
            # exactly as before (an EXPLICIT tenant= on a pool-less
            # tier still fails loudly — that is a misconfiguration)
            from .multiplex import request_tenant

            tenant = request_tenant()
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        deadline = (None if deadline_s is None
                    else time.perf_counter() + float(deadline_s))
        # flight recorder: adopt the gateway's trace when one is active
        # on this thread; mint our own for direct callers (and then we
        # own the finish). Either way the trace rides the thread-local
        # so every tier hop below stamps phases without plumbing.
        tr = reqtrace.current_trace()
        owned = tr is None
        if owned:
            tr = reqtrace.start_trace(source="router", tenant=tenant,
                                      cls=priority)
        try:
            with reqtrace.activate(tr):
                self._check_deadline(deadline, tenant)  # arrived expired
                # rep_box[0] is the decode replica currently holding
                # this request's reservation — failover swaps it, and
                # release-on-exit must decrement whichever replica
                # holds it NOW (releasing the original after a swap
                # would steal another request's reservation and leak
                # the survivor's)
                with reqtrace.phase("queue_reserve"):
                    rep_box = [self._admit_or_shed(tenant, deadline,
                                                   priority)]
                t_admit = time.perf_counter()
                pslot = self._preempt_register(priority, tenant)
                ok = False
                try:
                    if not self._disagg_mode:
                        out = self._generate_colocated(
                            prompt, max_new_tokens, eos_token,
                            timeout_s, deadline, on_first_token,
                            token_sleep_s, t_admit, tenant, pslot,
                            on_tokens, cancel_event, rep_box)
                    else:
                        out = self._generate_disagg(
                            rep_box, prompt, max_new_tokens, eos_token,
                            timeout_s, deadline, on_first_token,
                            token_sleep_s, t_admit, tenant, pslot,
                            on_tokens, cancel_event)
                    ok = True
                    if owned and tr is not None:
                        tr.finish("ok", tokens=len(out))
                    return out
                finally:
                    self._preempt_unregister(pslot)
                    self._complete(rep_box[0], ok, tenant=tenant,
                                   wall_ms=(time.perf_counter()
                                            - t_admit) * 1e3)
        except RequestShedError as e:
            if owned and tr is not None:
                cause = getattr(e, "cause", None)
                outcome = {"deadline": "deadline",
                           "disconnect": "disconnect",
                           "preempt": "preempt"}.get(cause, "shed")
                tr.finish(outcome, cause=cause)
            raise
        except BaseException as e:
            if owned and tr is not None:
                tr.finish("error", cause=type(e).__name__)
            raise

    def _record_tenant_ttft(self, tenant: Optional[str],
                            ttft_ms: float) -> None:
        if tenant is None:
            return
        with self._lock:
            rec = self._tenant_rec_locked(tenant)
            rec["ttft"].add(ttft_ms)
            if ttft_ms > self._tenant_slo_ms:
                rec["slo_misses"] += 1

    def _generate_colocated(self, prompt, max_new_tokens, eos_token,
                            timeout_s, deadline, on_first_token,
                            token_sleep_s, t_admit, tenant=None,
                            pslot=None, on_tokens=None,
                            cancel_event=None,
                            rep_box=None) -> List[int]:
        """Single-engine path — now a replay LOOP mirroring
        _generate_disagg: a preempted batch stream ends early at the
        engine's tick boundary (cancelled slots drain through _DONE)
        and resumes here from prompt+history for the remaining budget,
        bit-identical under greedy decode."""
        history: List[int] = []
        first_emitted = False
        had_preempt = False
        tr = reqtrace.current_trace()
        while True:
            remaining = max_new_tokens - len(history)
            if remaining <= 0:
                break
            if eos_token is not None and history \
                    and history[-1] == int(eos_token):
                break  # complete before the cancel landed
            replay = (np.concatenate(
                [prompt, np.asarray(history, np.int32)])
                if history else prompt)
            try:
                stream = self._colocated.stream(replay, remaining,
                                                eos_token,
                                                timeout_s=timeout_s,
                                                adapter_id=tenant)
            except Exception as e:  # noqa: BLE001 — submit-time failure
                if _is_pool_exhausted(e):
                    raise self._shed_pool_exhausted("colocated", tenant,
                                                    e) from e
                raise
            if pslot is not None:
                # arm preemption for the live stream: the cancel is
                # reason-tagged so engine accounting attributes it
                with self._lock:
                    pslot.rep = rep_box[0] if rep_box else None
                    pslot.cancel_fn = (
                        lambda s=stream: self._colocated.cancel_slot(
                            s, "preempt"))
            t_dec = time.perf_counter()
            t_first_tok: Optional[float] = None
            n_attempt_toks = 0
            try:
                for tok in stream:
                    if t_first_tok is None:
                        t_first_tok = time.perf_counter()
                        if tr is not None:
                            tr.add_phase("decode_first_token",
                                         (t_first_tok - t_dec) * 1e3)
                    n_attempt_toks += 1
                    if not first_emitted:
                        first_emitted = True
                        ttft = (time.perf_counter() - t_admit) * 1e3
                        self._ttft_win.add(ttft)
                        self._record_tenant_ttft(tenant, ttft)
                        if on_first_token is not None:
                            on_first_token()
                    history.append(tok)
                    if pslot is not None:
                        pslot.tokens = len(history)
                    if on_tokens is not None:
                        try:
                            on_tokens([tok])
                        except Exception:  # noqa: BLE001 — caller's
                            pass
                    if token_sleep_s > 0:
                        time.sleep(token_sleep_s)
                    self._check_abort(deadline, tenant, cancel_event)
            except RequestShedError as e:
                # deadline/disconnect shed mid-stream: cancel the
                # engine slot so the abandoned request stops burning
                # ticks (freed + pins released at the tick boundary)
                if tr is not None:
                    tr.add_phase(
                        "decode_steady" if t_first_tok is not None
                        else "decode_first_token",
                        (time.perf_counter()
                         - (t_first_tok or t_dec)) * 1e3,
                        tokens=n_attempt_toks,
                        error=getattr(e, "cause", None) or "shed")
                cancel = getattr(self._colocated, "cancel_slot", None)
                if callable(cancel):
                    cancel(stream, getattr(e, "cause", None))
                raise
            finally:
                if pslot is not None:
                    with self._lock:
                        pslot.cancel_fn = None
            if tr is not None and t_first_tok is not None:
                tr.add_phase("decode_steady",
                             (time.perf_counter() - t_first_tok) * 1e3,
                             tokens=n_attempt_toks)
            if pslot is not None and pslot.preempted \
                    and len(history) < max_new_tokens \
                    and not (eos_token is not None and history
                             and history[-1] == int(eos_token)):
                # the stream ended early because an interactive
                # request took the slot — resume, don't return short
                with self._lock:
                    pslot.preempted = False
                had_preempt = True
                if tr is not None:
                    tr.mark_preempt()
                time.sleep(0.1)  # let the preemptor actually land
                continue
            break
        if had_preempt:
            with self._lock:
                self._stats["preempted_requests"] += 1
        return history

    def _generate_disagg(self, rep_box, prompt, max_new_tokens,
                         eos_token, timeout_s, deadline, on_first_token,
                         token_sleep_s, t_admit, tenant=None,
                         pslot=None, on_tokens=None,
                         cancel_event=None) -> List[int]:
        """The failover loop. `history` holds every token delivered so
        far; a replay prefills prompt+history (a suffix-only prefill
        thanks to the prefix cache — the dead replica's tokens EXTEND
        the prompt) and resumes decode for the remaining budget, so the
        concatenated stream is bit-identical to an uninterrupted greedy
        run. `rep_box[0]` tracks the decode replica holding the
        request's reservation across swaps; the caller releases it.

        A QoS preemption (`pslot` marked preempted, its stream
        cancelled under it) rides the SAME loop: the victim's pull
        ends early — done short of budget from an in-flight pull, or
        KeyError once the handle is popped — and the next iteration
        replays exactly like a failover, without consuming a failover
        attempt or moving the reservation."""
        history: List[int] = []
        attempt = 0
        first_emitted = False
        fail_detected: Optional[float] = None
        had_failover = False
        had_preempt = False
        tr = reqtrace.current_trace()

        def _preempt_resume() -> bool:
            """True exactly once per fired preemption: the stream
            ended early because an interactive request took the slot
            (not death, not completion) — resume, don't fail over."""
            nonlocal had_preempt
            if pslot is None or not pslot.preempted:
                return False
            if len(history) >= max_new_tokens or (
                    eos_token is not None and history
                    and history[-1] == int(eos_token)):
                return False  # complete anyway; nothing to resume
            with self._lock:
                pslot.preempted = False
            had_preempt = True
            if tr is not None:
                # the replay's phases become a child span set under
                # the same request id, tagged with the new attempt
                tr.mark_preempt()
            time.sleep(0.1)  # let the preemptor actually land
            return True

        while True:
            rep = rep_box[0]
            attempt += 1
            self._check_abort(deadline, tenant, cancel_event)
            remaining = max_new_tokens - len(history)
            if remaining <= 0:
                return history  # died between last token and DONE
            if eos_token is not None and history \
                    and history[-1] == int(eos_token):
                # the eos token was already delivered — the replica
                # died between the eos pull and the done pull. The
                # request IS complete; replaying would decode past eos
                # and break bit-identity.
                return history
            replay = (np.concatenate(
                [prompt, np.asarray(history, np.int32)])
                if history else prompt)
            # ---- prefill phase (retryable: nothing emitted from rec
            # until decode pulls it)
            pf, kv_hint = self._pick_prefill_or_wait(
                replay, rep.machine, deadline, tenant)
            with self._lock:
                self._pf_inflight += 1
                pf.inflight += 1
            self._pf_inflight_win.add(self._pf_inflight)
            try:
                # the tier-3 hint rides as an extra positional only
                # when present — pre-kvplane replicas (and test
                # doubles) keep their two-argument prefill surface
                pf_args = (replay.tolist(), tenant) \
                    if kv_hint is None \
                    else (replay.tolist(), tenant, kv_hint)
                with reqtrace.phase("prefill", replica=pf.rid,
                                    prompt_tokens=int(replay.size)):
                    rec = self._tier_call(pf, "prefill", "prefill",
                                          *pf_args)
            except Exception as e:  # noqa: BLE001 — dead or broken
                if _is_pool_exhausted(e):
                    raise self._shed_pool_exhausted("prefill", tenant,
                                                    e) from e
                self._check_request_fault(tenant, e)
                fail_detected = time.perf_counter()
                had_failover = True
                self._attempt_failed("prefill", pf.rid, attempt, e,
                                     tenant)
                if tr is not None:
                    tr.begin_attempt()
                continue
            finally:
                with self._lock:
                    self._pf_inflight -= 1
                    if pf.inflight > 0:
                        pf.inflight -= 1
            try:
                if tr is not None and rec.get("kvplane"):
                    # tier-2/3 fetch sub-phases: the flight recorder
                    # attributes KV-plane time inside the prefill span
                    for tier_n, ph in (("tier2",
                                        "kvplane_tier2_fetch"),
                                       ("tier3",
                                        "kvplane_tier3_fetch")):
                        tinfo = rec["kvplane"].get(tier_n)
                        if tinfo:
                            tr.add_phase(
                                ph, float(tinfo.get("ms", 0.0)),
                                replica=pf.rid,
                                blocks=int(tinfo.get("blocks", 0)),
                                tokens=int(tinfo.get("tokens", 0)),
                                kv_bytes=int(tinfo.get("nbytes", 0)))
                if not first_emitted:
                    # the first token exists NOW — this is the TTFT
                    # the recent window (and the policy's queueing-
                    # delay signal) reads
                    first_emitted = True
                    ttft = (time.perf_counter() - t_admit) * 1e3
                    self._ttft_win.add(ttft)
                    self._record_tenant_ttft(tenant, ttft)
                    self._cache_win.add(
                        _OUTCOME_WEIGHT.get(rec.get("outcome"), 0.0))
                    if on_first_token is not None:
                        on_first_token()
                if fail_detected is not None:
                    # recovery cost: failure detection -> replayed
                    # prefill landed (the stream is about to resume)
                    self._failover_win.add(
                        (time.perf_counter() - fail_detected) * 1e3)
                    fail_detected = None
            except BaseException:
                # a raising caller callback must not strand the
                # just-published transfer un-acked (it would pin the
                # sender's chunk refs forever on a quiet tier)
                self._ack_transfer(pf, rec)
                raise
            # ---- decode phase: chunked pulls so the router holds the
            # history the next replay would need
            hid = None
            # slow-client pacing sleeps token_sleep_s * chunk between
            # pulls; cap the chunk so the inter-pull gap stays well
            # inside the replica's idle-reap window (the reap stamp
            # refreshes on every pull) — without this, pacing past
            # _STREAM_REAP_S / chunk would reap a healthy live stream
            chunk = self.stream_chunk_tokens
            if token_sleep_s > 0:
                chunk = max(1, min(chunk,
                                   int(120.0 / token_sleep_s) or 1))
            t_dec: Optional[float] = None
            t_first_tok: Optional[float] = None
            n_attempt_toks = 0
            spec_attrs: Dict[str, int] = {}
            try:
                if tr is not None:
                    # remote decode tiers (actor mode) push their KV
                    # adoption breakdown to the conductor as a child
                    # phase under this id; local tiers annotate the
                    # open kv_transfer span directly
                    rec["_reqtrace"] = {"request_id": tr.request_id,
                                        "attempt": attempt}
                with reqtrace.phase("kv_transfer", replica=rep.rid):
                    hid = self._tier_call(rep, "decode", "start_decode",
                                          rec, remaining, eos_token,
                                          timeout_s)
                t_dec = time.perf_counter()
                if pslot is not None:
                    # arm preemption for the LIVE stream only: an
                    # interactive arrival cancels exactly this handle
                    # (reason-tagged so engine accounting attributes
                    # it) and rides the freed slot
                    with self._lock:
                        pslot.rep = rep
                        pslot.cancel_fn = (
                            lambda r=rep, h=hid: _call(  # shardlint: disable=unsupervised-actor-call
                                r.target, "cancel_decode", h,
                                "preempt", block=False))
                last_progress = time.perf_counter()
                while True:
                    out = self._tier_call(
                        rep, "decode", "next_tokens", hid, chunk,
                        min(2.0, max(0.1, timeout_s / 4)))
                    toks = out.get("tokens") or []
                    if toks:
                        if t_first_tok is None:
                            t_first_tok = time.perf_counter()
                            if tr is not None:
                                tr.add_phase("decode_first_token",
                                             (t_first_tok - t_dec)
                                             * 1e3, replica=rep.rid)
                        n_attempt_toks += len(toks)
                        history.extend(int(t) for t in toks)
                        if pslot is not None:
                            pslot.tokens = len(history)
                        if on_tokens is not None:
                            # the gateway's SSE bridge; its bugs (or a
                            # closed queue) must not kill the decode
                            try:
                                on_tokens([int(t) for t in toks])
                            except Exception:  # noqa: BLE001
                                pass
                        last_progress = time.perf_counter()
                        if token_sleep_s > 0:
                            time.sleep(token_sleep_s * len(toks))
                    if out.get("done"):
                        if tr is not None:
                            for k in ("spec_proposed", "spec_accepted"):
                                if out.get(k) is not None:
                                    spec_attrs[k] = int(out[k])
                            tr.add_phase(
                                "decode_steady",
                                (time.perf_counter()
                                 - (t_first_tok or t_dec)) * 1e3,
                                replica=rep.rid, tokens=n_attempt_toks,
                                **spec_attrs)
                        self._ack_transfer(pf, rec)
                        if _preempt_resume():
                            # the cancel landed mid-pull: this "done"
                            # is the cancelled slot draining, not
                            # completion — replay from history
                            break
                        if had_failover:
                            with self._lock:
                                self._sf["failover_requests"] += 1
                            self.publish_servefault()
                        if had_preempt:
                            with self._lock:
                                self._stats[
                                    "preempted_requests"] += 1
                        return history
                    try:
                        self._check_abort(deadline, tenant,
                                          cancel_event)
                    except RequestShedError as e:
                        # abandon the stream: the engine frees the slot
                        # on its own; the transfer is still acked so
                        # the sender's chunk refs never leak
                        if tr is not None and t_dec is not None:
                            tr.add_phase(
                                "decode_steady"
                                if t_first_tok is not None
                                else "decode_first_token",
                                (time.perf_counter()
                                 - (t_first_tok or t_dec)) * 1e3,
                                replica=rep.rid,
                                tokens=n_attempt_toks,
                                error=getattr(e, "cause", None)
                                or "shed")
                        try:
                            self._tier_call(rep, "decode",
                                            "cancel_decode", hid,
                                            getattr(e, "cause", None),
                                            block=False)
                        except Exception:  # noqa: BLE001 — dead too
                            pass
                        self._ack_transfer(pf, rec)
                        raise
                    if time.perf_counter() - last_progress > timeout_s:
                        raise TimeoutError(
                            f"decode stream stalled > {timeout_s:.0f}s "
                            f"on {rep.rid}")
            except RequestShedError:
                raise
            except Exception as e:  # noqa: BLE001 — death or stall
                if tr is not None and t_dec is not None:
                    # the failed attempt's partial decode IS a child
                    # span — the failover breakdown needs it
                    tr.add_phase(
                        "decode_steady" if t_first_tok is not None
                        else "decode_first_token",
                        (time.perf_counter()
                         - (t_first_tok or t_dec)) * 1e3,
                        replica=rep.rid, tokens=n_attempt_toks,
                        error=type(e).__name__)
                if _preempt_resume():
                    # not a fault: the pull handle vanished because an
                    # interactive request took the slot (cancel_decode
                    # pops it -> this KeyError). Resume WITHOUT
                    # consuming a failover attempt or moving the
                    # reservation — the replica is alive.
                    self._ack_transfer(pf, rec)
                    continue
                if _is_pool_exhausted(e):
                    self._ack_transfer(pf, rec)
                    raise self._shed_pool_exhausted("decode", tenant,
                                                    e) from e
                try:
                    self._check_request_fault(tenant, e)
                except ValueError:
                    self._ack_transfer(pf, rec)
                    raise
                fail_detected = time.perf_counter()
                had_failover = True
                if hid is not None:
                    # a LIVE-but-stalled replica keeps its abandoned
                    # stream (and the engine slot behind it) unless we
                    # cancel; on a dead replica this is a no-op throw
                    try:
                        _call(rep.target, "cancel_decode", hid,  # shardlint: disable=unsupervised-actor-call
                              "failover", block=False)
                    except Exception:  # noqa: BLE001 — replica dead
                        pass
                self._ack_transfer(pf, rec)
                self._attempt_failed("decode", rep.rid, attempt, e,
                                     tenant)
                if tr is not None:
                    tr.begin_attempt()
                rep_box[0] = self._reserve_survivor(rep, deadline,
                                                    tenant)
                continue
            finally:
                if pslot is not None:
                    with self._lock:
                        pslot.cancel_fn = None

    # ------------------------------------------------------------ telemetry

    def reset_signal_windows(self) -> None:
        """Fresh recent-signal windows. Callers that warm compile
        caches through the router (bench_serve's off-the-clock phase)
        reset before attaching an autoscaler — multi-second first
        compiles would otherwise read as a TTFT-SLO breach for a whole
        window and trigger spurious scale-ups."""
        self._ttft_win = SlidingWindow()
        self._depth_win = SlidingWindow()
        self._pf_inflight_win = SlidingWindow()
        self._cache_win = SlidingWindow()

    def signals(self) -> Dict[str, Any]:
        """The autoscale policy's input snapshot (recent windows; keys
        absent when there is no evidence yet — see
        serve/autoscale.DisaggPolicy for what each drives)."""
        sig: Dict[str, Any] = {}
        ttft = self._ttft_win.summary()
        if ttft["n"]:
            sig["ttft_p99_ms"] = ttft["p99"]
        depth = self._depth_win.summary()
        if depth["n"]:
            sig["queue_depth_p99"] = depth["p99"]
        pf = self._pf_inflight_win.summary()
        if pf["n"]:
            sig["prefill_inflight_p99"] = pf["p99"]
        cache = self._cache_win.summary()
        if cache["n"]:
            sig["cache_hit_rate"] = cache["mean"]
        return sig

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            s: Dict[str, Any] = dict(self._stats)
            s["pending"] = sum(r.inflight for r in self._decode)
            s["failovers"] = dict(self._sf["failovers"])
            s["failover_requests"] = self._sf["failover_requests"]
            s["sheds_by_cause"] = dict(self._sf["sheds_by_cause"])
            decode = list(self._decode)
            prefill = list(self._prefill)
        s.update(role="router", router_id=self.router_id,
                 mode="disagg" if self._disagg_mode else "colocated",
                 decode_replicas=sum(1 for r in decode
                                     if not r.draining),
                 prefill_replicas=sum(1 for r in prefill
                                      if not r.draining),
                 draining_replicas=sum(
                     1 for r in decode + prefill if r.draining),
                 capacity=sum(r.cap for r in decode if not r.draining),
                 max_queue_depth=self.max_queue_depth,
                 retry_after_s=self.retry_after_s)
        if s["shm_affinity_total"]:
            s["shm_affinity_hit_rate"] = round(
                s["shm_affinity_hits"] / s["shm_affinity_total"], 4)
        if s["tenant_affinity_total"]:
            s["tenant_affinity_hit_rate"] = round(
                s["tenant_affinity_hits"] / s["tenant_affinity_total"],
                4)
        tenants = self.tenant_stats()
        if tenants:
            s["tenants"] = tenants
        # recent trailing-window summaries beside the lifetime counters
        # (`serve status`/CLI show both; the autoscale policy reads the
        # same derivation through signals())
        s["recent"] = {
            "ttft_ms": self._ttft_win.summary(),
            "queue_depth": self._depth_win.summary(),
            "prefill_inflight": self._pf_inflight_win.summary(),
            "cache_hit_rate": self._cache_win.summary(),
        }
        return s

    def tenant_stats(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant isolated counters (dispatched/completed/shed by
        cause/SLO misses + recent TTFT/latency windows) — the router's
        contribution to the lora surface, and the bench's isolation
        evidence."""
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for t, rec in self._tenant_stats.items():
                out[t] = {
                    "dispatched": rec["dispatched"],
                    "completed": rec["completed"],
                    "shed": rec["shed"],
                    "sheds_by_cause": dict(rec["sheds_by_cause"]),
                    "slo_misses": rec["slo_misses"],
                    "ttft_ms": rec["ttft"].summary(),
                    "latency_ms": rec["latency"].summary(),
                }
        return out

    def kvplane_stats(self) -> Dict[str, Any]:
        """The router's KV-plane contribution: directory routing
        outcomes (hit = routed to the holder, fallback = holder gone,
        hashed + tier-3 hint, miss = nothing published). Rates and
        totals merge with the replicas' tier stats on the conductor."""
        with self._lock:
            s: Dict[str, Any] = {
                k: self._stats[k] for k in
                ("directory_hits", "directory_misses",
                 "directory_fallbacks")}
        s.update(role="router", router_id=self.router_id,
                 enabled=self._kvplane_dir,
                 kv_block_size=self._kv_block_size)
        probes = (s["directory_hits"] + s["directory_misses"]
                  + s["directory_fallbacks"])
        if probes:
            s["directory_hit_rate"] = round(
                s["directory_hits"] / probes, 4)
        return s

    def publish_telemetry(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_push < 0.5:
            return
        self._last_push = now
        _push_stats(self.router_id, self.stats())
        if self._disagg_mode and self._kvplane_dir:
            w = _worker()
            if w is not None:
                try:
                    w.conductor.notify("report_kvplane_stats",
                                       w.worker_id, self.router_id,
                                       self.kvplane_stats())
                except Exception:  # noqa: BLE001 — shutting down
                    pass
        tenants = self.tenant_stats()
        if tenants:
            # the router's tenant counters ride the lora surface too,
            # beside the pools' paging stats (one aggregate, every
            # surface reads the same numbers)
            w = _worker()
            if w is not None:
                try:
                    w.conductor.notify(
                        "report_lora_stats", w.worker_id,
                        self.router_id,
                        {"role": "router", "router_id": self.router_id,
                         "tenant_affinity_hits":
                             self._stats["tenant_affinity_hits"],
                         "tenant_affinity_total":
                             self._stats["tenant_affinity_total"],
                         "tenants": tenants})
                except Exception:  # noqa: BLE001 — shutting down
                    pass

    def servefault_stats(self) -> Dict[str, Any]:
        """The fault-tolerance snapshot this router contributes to the
        servefault surface (state API == CLI == dashboard ==
        Prometheus == timeline read the same numbers)."""
        with self._lock:
            sf: Dict[str, Any] = {
                "failovers": dict(self._sf["failovers"]),
                "failover_requests": self._sf["failover_requests"],
                "sheds_by_cause": dict(self._sf["sheds_by_cause"]),
                "removed_dead": dict(self._sf["removed_dead"]),
            }
        sf.update(role="router", router_id=self.router_id,
                  recent_failover_recovery_ms=
                  self._failover_win.summary())
        return sf

    def publish_servefault(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_sf_push < 0.5:
            return
        self._last_sf_push = now
        _push_servefault(self.router_id, self.servefault_stats())


__all__ = ["DecodeServer", "DisaggRouter", "PrefillServer",
           "ReplicaDeadError", "RequestShedError", "disagg_metrics",
           "servefault_metrics"]
