"""Model multiplexing — analog of the reference's
python/ray/serve/multiplex.py (_ModelMultiplexWrapper) + api.py
(@serve.multiplexed, get_multiplexed_model_id).

A replica hosts up to N models, loaded on demand and evicted LRU. On TPU the
loader typically stages weights host->HBM with jax.device_put; eviction drops
the device arrays and lets XLA's allocator reclaim HBM."""
from __future__ import annotations

import collections
import functools
import threading
from typing import Any, Callable, Optional

from .context import get_request_context


def get_multiplexed_model_id() -> str:
    """The model id of the current request (from the
    'serve_multiplexed_model_id' header or handle option) — reference
    python/ray/serve/api.py get_multiplexed_model_id."""
    return get_request_context().multiplexed_model_id


def request_tenant() -> Optional[str]:
    """The current serve request's multiplexed-model-id, reused as the
    multi-tenant LoRA tenant tag (serve/lora.py): a deployment that
    already routes per-model via ``@serve.multiplexed`` gets per-tenant
    adapter serving with no new request plumbing —
    ``DisaggRouter.generate`` defaults its ``tenant=`` to this. None
    outside a request context or when the request carries no id."""
    try:
        mid = get_request_context().multiplexed_model_id
    except Exception:  # noqa: BLE001 — no request context here
        return None
    return mid or None


class _ModelCache:
    def __init__(self, loader: Callable[[Any, str], Any], max_models: int):
        self._loader = loader
        self._max = max_models
        self._cache: "collections.OrderedDict[str, Any]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()

    def __reduce__(self):
        # Per-process state (lock, loaded models) is rebuilt in the replica.
        return (_ModelCache, (self._loader, self._max))

    def get(self, self_arg, model_id: str) -> Any:
        with self._lock:
            if model_id in self._cache:
                self._cache.move_to_end(model_id)
                return self._cache[model_id]
        model = (self._loader(self_arg, model_id) if self_arg is not None
                 else self._loader(model_id))
        with self._lock:
            self._cache[model_id] = model
            self._cache.move_to_end(model_id)
            while len(self._cache) > self._max:
                old_id, old = self._cache.popitem(last=False)
                # Optional eviction hook (e.g. free HBM buffers eagerly);
                # plain models are simply dropped for GC.
                shutdown_fn = getattr(old, "shutdown", None)
                if callable(shutdown_fn):
                    try:
                        shutdown_fn()
                    except Exception:  # noqa: BLE001 — eviction best-effort
                        pass
        return model

    def model_ids(self):
        with self._lock:
            return list(self._cache.keys())


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator on a model-loading method ``def get_model(self, model_id)``;
    calls are LRU-cached per replica."""

    def deco(fn: Callable) -> Callable:
        from .batching import PerInstance
        caches = PerInstance(
            lambda: _ModelCache(fn, max_num_models_per_replica))

        @functools.wraps(fn)
        def wrapper(*args):
            if len(args) == 2:
                self_arg, model_id = args
            else:
                self_arg, model_id = None, args[0]
            return caches.get(self_arg).get(self_arg, model_id)

        wrapper._serve_model_caches = caches
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
