"""Serve configuration schemas — analog of the reference's
python/ray/serve/config.py and schema.py (pydantic there; plain dataclasses
here — no pydantic dependency in the TPU build)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Queue-length-driven autoscaling — reference
    python/ray/serve/config.py AutoscalingConfig + autoscaling_policy.py.
    Target replicas = ceil(total ongoing requests / target_ongoing_requests),
    clamped to [min_replicas, max_replicas], smoothed by upscale/downscale
    delays."""
    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 60.0
    metrics_interval_s: float = 0.5

    def validate(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                f"invalid autoscaling bounds [{self.min_replicas}, "
                f"{self.max_replicas}]")
        if self.target_ongoing_requests <= 0:
            raise ValueError("target_ongoing_requests must be > 0")


@dataclass
class DeploymentConfig:
    """Per-deployment config — reference serve/config.py DeploymentConfig."""
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    # admission control (router load shedding): each replica may hold
    # at most max_ongoing + max_queued_requests in-flight through a
    # handle; past that the router rejects with RequestShedError +
    # retry_after instead of queueing unboundedly. -1 disables (the
    # pre-admission behavior); RAY_TPU_SERVE_MAX_QUEUE_DEPTH overrides.
    max_queued_requests: int = -1
    user_config: Optional[Any] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 30.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def validate(self) -> None:
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_ongoing_requests < 1:
            raise ValueError("max_ongoing_requests must be >= 1")
        if self.max_queued_requests < -1:
            raise ValueError("max_queued_requests must be >= -1 "
                             "(-1 disables admission control)")
        if self.autoscaling_config is not None:
            self.autoscaling_config.validate()


@dataclass
class HTTPOptions:
    """Proxy options — reference python/ray/serve/config.py HTTPOptions
    (+ gRPCOptions folded in: grpc_port=None disables the gRPC ingress,
    matching the reference's opt-in gRPC proxy)."""
    host: str = "127.0.0.1"
    port: int = 8000
    grpc_port: Optional[int] = None
    # "EveryNode" runs a proxy replica on each cluster node (reference
    # ProxyLocation.EveryNode, proxy_state.py); "HeadOnly" restricts to
    # the head. Non-head proxies bind ephemeral ports; discover them via
    # serve.status()["proxies"].
    proxy_location: str = "EveryNode"
