"""Minimal HTTP request/response model shared by proxy and replicas —
analog of the reference's python/ray/serve/_private/http_util.py (which
adapts Starlette; the TPU build carries a plain picklable Request so it can
cross the proxy->replica actor boundary without an ASGI dependency)."""
from __future__ import annotations

import json as _json
from typing import Any, Dict, Optional, Tuple
from urllib.parse import parse_qsl


class Request:
    """What an ingress deployment's __call__ receives for HTTP requests."""

    def __init__(self, method: str, path: str, query_string: str = "",
                 headers: Optional[Dict[str, str]] = None,
                 body: bytes = b""):
        self.method = method
        self.path = path
        self.query_string = query_string
        self.headers = dict(headers or {})
        self.body = body

    @property
    def query_params(self) -> Dict[str, str]:
        return dict(parse_qsl(self.query_string))

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", "replace")

    def __repr__(self):
        return f"Request({self.method} {self.path})"


def coerce_response(result: Any) -> Tuple[int, Dict[str, str], bytes]:
    """Map a user return value to (status, headers, body) the way the
    reference proxy does for Starlette responses / raw returns."""
    if isinstance(result, tuple) and len(result) == 2 and \
            isinstance(result[0], int):
        status, payload = result
    else:
        status, payload = 200, result
    if isinstance(payload, bytes):
        return status, {"content-type": "application/octet-stream"}, payload
    if isinstance(payload, str):
        return status, {"content-type": "text/plain; charset=utf-8"}, \
            payload.encode()
    return status, {"content-type": "application/json"}, \
        _json.dumps(payload, default=str).encode()
