"""Minimal HTTP request/response model shared by proxy and replicas —
analog of the reference's python/ray/serve/_private/http_util.py (which
adapts Starlette; the TPU build carries a plain picklable Request so it can
cross the proxy->replica actor boundary without an ASGI dependency)."""
from __future__ import annotations

import json as _json
from typing import Any, Dict, List, Optional, Tuple  # noqa: F401
from urllib.parse import parse_qsl


class Request:
    """What an ingress deployment's __call__ receives for HTTP requests."""

    def __init__(self, method: str, path: str, query_string: str = "",
                 headers: Optional[Dict[str, str]] = None,
                 body: bytes = b""):
        self.method = method
        self.path = path
        self.query_string = query_string
        self.headers = dict(headers or {})
        self.body = body

    @property
    def query_params(self) -> Dict[str, str]:
        return dict(parse_qsl(self.query_string))

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", "replace")

    def __repr__(self):
        return f"Request({self.method} {self.path})"


class Response:
    """Explicit HTTP response with header control (the tuple/str/dict
    shorthands cannot carry headers) — what the ASGI ingress adapter
    returns, and available to plain deployments too.

    `headers` may be a dict or a list of (name, value) pairs; the list
    form preserves duplicates (multiple Set-Cookie headers)."""

    __slots__ = ("status", "headers", "body")

    def __init__(self, body: Any = b"", status: int = 200,
                 headers: Any = None):
        self.status = int(status)
        if headers is None:
            self.headers: List[Tuple[str, str]] = []
        elif isinstance(headers, dict):
            self.headers = [(str(k), str(v)) for k, v in headers.items()]
        else:
            self.headers = [(str(k), str(v)) for k, v in headers]
        if isinstance(body, str):
            body = body.encode()
        elif not isinstance(body, (bytes, bytearray)):
            body = _json.dumps(body, default=str).encode()
        self.body = bytes(body)


def coerce_response(result: Any) -> Tuple[int, Any, bytes]:
    """Map a user return value to (status, headers, body) the way the
    reference proxy does for Starlette responses / raw returns. Headers
    come back as a dict for the shorthand forms and as a list of pairs
    (duplicate-preserving) for Response objects."""
    if isinstance(result, Response):
        return result.status, result.headers, result.body
    if isinstance(result, tuple) and len(result) == 2 and \
            isinstance(result[0], int):
        status, payload = result
    else:
        status, payload = 200, result
    if isinstance(payload, bytes):
        return status, {"content-type": "application/octet-stream"}, payload
    if isinstance(payload, str):
        return status, {"content-type": "text/plain; charset=utf-8"}, \
            payload.encode()
    return status, {"content-type": "application/json"}, \
        _json.dumps(payload, default=str).encode()
