"""Replica actor — analog of the reference's python/ray/serve/_private/
replica.py (ReplicaActor :231, handle_request :390, UserCallableWrapper).

One replica = one actor with max_concurrency = max_ongoing_requests; the
queue-length it reports (num ongoing requests) drives both the pow-2 router
and the controller's autoscaler, mirroring the reference's
ReplicaMetricsManager."""
from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from .context import RequestContext, set_request_context
from .http_util import Request  # noqa: F401 — re-export for user callables

# Replica-side data-plane telemetry (one set of metric objects per
# process; replicas are one-per-process so the WorkerId label already
# distinguishes them). They ride the util.metrics conductor-push
# pipeline into /api/metrics and `ray_tpu metrics`.
_metrics_cache: Dict[str, Any] = {}
_metrics_lock = threading.Lock()

_LATENCY_BOUNDS_MS = [1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0,
                      1000.0, 2500.0, 5000.0, 10000.0]


def _replica_metrics() -> Dict[str, Any]:
    # double-checked init: the unlocked read is the per-request fast
    # path; the lock only guards first-time registration so two racing
    # first requests cannot both register metric objects (duplicate
    # identical-labelset Prometheus series)
    if _metrics_cache:
        return _metrics_cache
    with _metrics_lock:
        if not _metrics_cache:
            _build_metrics()
    return _metrics_cache


def _build_metrics() -> None:
    from ray_tpu.util.metrics import Counter, Gauge, Histogram

    tags = ("app", "deployment")
    _metrics_cache.update(
        latency=Histogram(
            "serve_request_latency_ms",
            "end-to-end request latency on the replica",
            boundaries=_LATENCY_BOUNDS_MS, tag_keys=tags),
        # `cache` labels prefix-cache wins (hit|partial|miss, "" for
        # streams that did not come from the batching engine) so the
        # paged KV cache shows up in the existing latency pipeline
        ttft=Histogram(
            "serve_ttft_ms",
            "time to first streamed chunk (streaming requests)",
            boundaries=_LATENCY_BOUNDS_MS, tag_keys=tags + ("cache",)),
        requests=Counter(
            "serve_requests_total", "requests handled",
            tag_keys=tags + ("outcome",)),
        inflight=Gauge(
            "serve_replica_inflight",
            "requests currently executing on this replica",
            tag_keys=tags + ("replica",)))


class HandleMarker:
    """Placeholder for a bound sub-deployment inside serialized init args;
    swapped for a live DeploymentHandle in the replica (reference: Serve
    replaces DeploymentNode args with handles at build time)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name


def _resolve_markers(obj: Any, app_name: str) -> Any:
    from .handle import DeploymentHandle
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.deployment_name, app_name)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_markers(x, app_name) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v, app_name) for k, v in obj.items()}
    return obj


def _as_iterator(result: Any):
    """An iterator over `result` if it is a streamable producer (sync or
    async generator, or any non-container iterator); None for plain
    values. Containers (str/bytes/list/dict/...) are values, not streams."""
    import types

    if isinstance(result, types.AsyncGeneratorType):
        return _drain_async_gen(result)
    if isinstance(result, types.GeneratorType):
        return result
    if hasattr(result, "__next__"):
        return result
    return None


def _loop_runner():
    """(run, owns_loop, loop): `run(coro)` resolves a coroutine on the
    ACTOR's persistent event loop when one exists — the same loop async
    methods run on, so loop-bound primitives from async init keep
    working — else on a private loop the caller must close."""
    import asyncio

    from ray_tpu._private.worker import global_worker

    rt = getattr(global_worker, "_actor_runtime", None)
    if rt is not None:
        loop = rt.ensure_loop()

        def run(coro):
            return asyncio.run_coroutine_threadsafe(coro, loop).result()

        return run, False, loop
    loop = asyncio.new_event_loop()
    return loop.run_until_complete, True, loop


def _run_coro(coro, request_ctx=None):
    """Resolve one coroutine, re-applying the request context INSIDE the
    loop thread — run_coroutine_threadsafe tasks capture the loop
    thread's contextvars, not the submitting request thread's, so
    get_request_context() would otherwise read empty inside async
    methods."""
    async def with_ctx():
        token = None
        if request_ctx is not None:
            token = set_request_context(request_ctx)
        try:
            return await coro
        finally:
            if token is not None:
                from .context import _request_context

                _request_context.reset(token)

    run, owns_loop, loop = _loop_runner()
    try:
        return run(with_ctx())
    finally:
        if owns_loop:
            loop.close()


def _drain_async_gen(agen):
    """Sync iterator over an async generator (see _loop_runner for the
    loop-affinity rationale)."""
    run, owns_loop, loop = _loop_runner()
    try:
        while True:
            try:
                yield run(agen.__anext__())
            except StopAsyncIteration:
                break
    finally:
        try:
            run(agen.aclose())
        except Exception:  # noqa: BLE001 — best-effort close
            pass
        if owns_loop:
            loop.close()


class ReplicaActor:
    """Hosts the user callable (class instance or plain function)."""

    def __init__(self, replica_tag: str, deployment_name: str, app_name: str,
                 serialized_callable: bytes, init_args: bytes,
                 user_config: Optional[Any] = None):
        from .autoscale import SlidingWindow

        self.replica_tag = replica_tag
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._inflight = 0
        self._draining = False
        self._lock = threading.Lock()
        self._num_requests = 0
        self._num_errors = 0
        self._start_time = time.time()
        self._tags = {"app": app_name, "deployment": deployment_name}
        # trailing-window twins of the cumulative Prometheus histograms:
        # `serve status` (and the autoscaling signal path) read RECENT
        # p50/p99, which a lifetime histogram can't give once load
        # shifts (serve/autoscale.SlidingWindow, shared derivation)
        self._recent_latency = SlidingWindow()
        self._recent_ttft = SlidingWindow()

        target = cloudpickle.loads(serialized_callable)
        args, kwargs = cloudpickle.loads(init_args)
        args = _resolve_markers(args, app_name)
        kwargs = _resolve_markers(kwargs, app_name)
        if isinstance(target, type):
            self._callable = target(*args, **kwargs)
            self._is_function = False
        else:
            self._callable = target
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)

    # -- data plane ---------------------------------------------------------
    def _invoke(self, meta: Dict[str, Any], args: List[Any],
                kwargs: Dict[str, Any]) -> Any:
        """Run the user callable under the request context (no in-flight
        accounting — callers hold it for their full request lifetime)."""
        # Resolve composed DeploymentResponse refs (they arrive nested inside
        # the args list, below the depth the worker auto-resolves).
        import ray_tpu
        from ray_tpu import ObjectRef
        args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                for a in args]
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        rc = RequestContext(
            route=meta.get("route", ""),
            app_name=meta.get("app_name", self.app_name),
            multiplexed_model_id=meta.get("multiplexed_model_id", ""))
        token = set_request_context(rc)
        try:
            if self._is_function:
                fn = self._callable
            else:
                method_name = meta.get("call_method") or "__call__"
                fn = getattr(self._callable, method_name, None)
                if fn is None:
                    raise AttributeError(
                        f"deployment {self.deployment_name} has no method "
                        f"'{method_name}'")
            result = fn(*args, **kwargs)
            if inspect.iscoroutine(result):
                # async callables (incl. the ASGI ingress adapter and
                # async function deployments) resolve on the actor's
                # persistent loop, with the request context re-applied
                # inside the loop thread
                result = _run_coro(result, rc)
            return result
        finally:
            from .context import _request_context
            _request_context.reset(token)

    def _track(self, t0: float, outcome: str,
               ttft_s: Optional[float] = None,
               cache_label: Optional[str] = None) -> None:
        """Record one finished request into the Prometheus pipeline.
        Runs in the request paths' finally blocks, so it must never
        raise: a telemetry failure would discard a computed response or
        shadow the request's real exception."""
        try:
            if outcome == "error":
                with self._lock:
                    self._num_errors += 1
            m = _replica_metrics()
            latency_ms = (time.perf_counter() - t0) * 1e3
            m["latency"].observe(latency_ms, tags=self._tags)
            self._recent_latency.add(latency_ms)
            if ttft_s is not None:
                m["ttft"].observe(ttft_s * 1e3,
                                  tags=dict(self._tags,
                                            cache=cache_label or ""))
                self._recent_ttft.add(ttft_s * 1e3)
            m["requests"].inc(1, tags=dict(self._tags, outcome=outcome))
            m["inflight"].set(self._inflight,
                              tags=dict(self._tags,
                                        replica=self.replica_tag))
        except Exception:  # noqa: BLE001 — telemetry must not fail a
            pass           # request or mask its real error

    def _reject_if_draining(self) -> None:
        """A request dispatched after this replica began its grace
        drain raced teardown: reject it with an ATTRIBUTED cause (the
        serving fault-tolerance invariant — never silently race the
        actor's death) so the handle retries on a live replica."""
        from .handle import RequestShedError

        with self._lock:
            draining = self._draining
        if draining:
            raise RequestShedError(
                f"replica {self.replica_tag} is draining for shutdown",
                retry_after_s=0.1, cause="draining")

    def handle_request(self, meta: Dict[str, Any], args: List[Any],
                       kwargs: Dict[str, Any]) -> Any:
        t0 = time.perf_counter()
        outcome = "ok"
        self._reject_if_draining()
        with self._lock:
            self._inflight += 1
            self._num_requests += 1
        try:
            return self._invoke(meta, args, kwargs)
        except BaseException:
            outcome = "error"
            raise
        finally:
            with self._lock:
                self._inflight -= 1
            self._track(t0, outcome)

    # every _ACK_EVERY-th chunk is a synchronous call instead of a notify:
    # bounds unacked in-flight data and detects a vanished consumer
    _ACK_EVERY = 64

    def handle_request_streaming(self, meta: Dict[str, Any], args: List[Any],
                                 kwargs: Dict[str, Any], stream_id: str,
                                 caller_addr) -> Any:
        """Streaming request path (reference replica.py:470
        handle_request_streaming). A generator/iterator result is pushed
        chunk-by-chunk straight to the caller's worker RPC server via
        stream_chunk frames and the final reply is ("gen", n_chunks); a
        plain result skips the stream entirely and comes back as
        ("value", result) — so the proxy can route EVERY request through
        here, like the reference's everything-streams HTTP proxy.

        In-flight accounting covers the whole generation, keeping pow-2
        routing and autoscaling honest for long streams."""
        from ray_tpu._private import serialization
        from ray_tpu._private.worker import global_worker

        t0 = time.perf_counter()
        outcome, ttft, cache_label = "ok", None, None
        self._reject_if_draining()
        with self._lock:
            self._inflight += 1
            self._num_requests += 1
        try:
            result = self._invoke(meta, args, kwargs)
            it = _as_iterator(result)
            if it is None:
                return ("value", result)
            client = global_worker.clients.get(tuple(caller_addr))
            seq = 0
            try:
                for item in it:
                    payload = serialization.dumps(item)
                    if ttft is None:  # first token/chunk produced
                        ttft = time.perf_counter() - t0
                        # batching-engine streams label their TTFT with
                        # the admission's prefix-cache outcome
                        # (engine.TokenStream.cache_outcome)
                        cache_label = getattr(it, "cache_outcome", None)
                    if (seq + 1) % self._ACK_EVERY == 0:
                        if not client.call("stream_chunk", stream_id, seq,
                                           payload, timeout=60.0):
                            break  # consumer closed the stream
                    else:
                        client.notify("stream_chunk", stream_id, seq, payload)
                    seq += 1
            finally:
                closer = getattr(it, "close", None)
                if callable(closer):
                    closer()
            return ("gen", seq)
        except BaseException:
            outcome = "error"
            raise
        finally:
            with self._lock:
                self._inflight -= 1
            self._track(t0, outcome, ttft_s=ttft, cache_label=cache_label)

    # -- control plane ------------------------------------------------------
    def get_queue_len(self) -> int:
        return self._inflight

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            out = {"replica_tag": self.replica_tag,
                   "inflight": self._inflight,
                   "num_requests": self._num_requests,
                   "num_errors": self._num_errors,
                   "uptime_s": time.time() - self._start_time}
        # recent trailing-window summaries beside the lifetime counters
        # (piggybacked to the controller on the health cadence; shown
        # by `serve status` and read by the autoscaling signal path)
        out["recent"] = {"latency_ms": self._recent_latency.summary(),
                         "ttft_ms": self._recent_ttft.summary()}
        return out

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if callable(fn):
            fn()
        return True

    def reconfigure(self, user_config: Any) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)

    async def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain in-flight requests — reference replica.py
        perform_graceful_shutdown. Async so the drain wait runs on the
        actor's event loop via `await asyncio.sleep` (shardlint
        blocking-in-async: a time.sleep poll here pinned one of the
        replica's request threads for the whole drain window)."""
        import asyncio

        with self._lock:
            # new arrivals now shed with cause "draining" instead of
            # racing the actor's death (the handle retries elsewhere)
            self._draining = True
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            await asyncio.sleep(0.05)
        # Optional user shutdown hook; __del__ is left to GC so
        # non-idempotent destructors don't run twice.
        fn = getattr(self._callable, "shutdown", None)
        if callable(fn):
            try:
                fn()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass
        return True
