"""Replica actor — analog of the reference's python/ray/serve/_private/
replica.py (ReplicaActor :231, handle_request :390, UserCallableWrapper).

One replica = one actor with max_concurrency = max_ongoing_requests; the
queue-length it reports (num ongoing requests) drives both the pow-2 router
and the controller's autoscaler, mirroring the reference's
ReplicaMetricsManager."""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import cloudpickle

from .context import RequestContext, set_request_context
from .http_util import Request  # noqa: F401 — re-export for user callables


class HandleMarker:
    """Placeholder for a bound sub-deployment inside serialized init args;
    swapped for a live DeploymentHandle in the replica (reference: Serve
    replaces DeploymentNode args with handles at build time)."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name


def _resolve_markers(obj: Any, app_name: str) -> Any:
    from .handle import DeploymentHandle
    if isinstance(obj, HandleMarker):
        return DeploymentHandle(obj.deployment_name, app_name)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_resolve_markers(x, app_name) for x in obj)
    if isinstance(obj, dict):
        return {k: _resolve_markers(v, app_name) for k, v in obj.items()}
    return obj


class ReplicaActor:
    """Hosts the user callable (class instance or plain function)."""

    def __init__(self, replica_tag: str, deployment_name: str, app_name: str,
                 serialized_callable: bytes, init_args: bytes,
                 user_config: Optional[Any] = None):
        self.replica_tag = replica_tag
        self.deployment_name = deployment_name
        self.app_name = app_name
        self._inflight = 0
        self._lock = threading.Lock()
        self._num_requests = 0
        self._start_time = time.time()

        target = cloudpickle.loads(serialized_callable)
        args, kwargs = cloudpickle.loads(init_args)
        args = _resolve_markers(args, app_name)
        kwargs = _resolve_markers(kwargs, app_name)
        if isinstance(target, type):
            self._callable = target(*args, **kwargs)
            self._is_function = False
        else:
            self._callable = target
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)

    # -- data plane ---------------------------------------------------------
    def handle_request(self, meta: Dict[str, Any], args: List[Any],
                       kwargs: Dict[str, Any]) -> Any:
        with self._lock:
            self._inflight += 1
            self._num_requests += 1
        # Resolve composed DeploymentResponse refs (they arrive nested inside
        # the args list, below the depth the worker auto-resolves).
        import ray_tpu
        from ray_tpu import ObjectRef
        args = [ray_tpu.get(a) if isinstance(a, ObjectRef) else a
                for a in args]
        kwargs = {k: (ray_tpu.get(v) if isinstance(v, ObjectRef) else v)
                  for k, v in kwargs.items()}
        token = set_request_context(RequestContext(
            route=meta.get("route", ""),
            app_name=meta.get("app_name", self.app_name),
            multiplexed_model_id=meta.get("multiplexed_model_id", "")))
        try:
            if self._is_function:
                return self._callable(*args, **kwargs)
            method_name = meta.get("call_method") or "__call__"
            method = getattr(self._callable, method_name, None)
            if method is None:
                raise AttributeError(
                    f"deployment {self.deployment_name} has no method "
                    f"'{method_name}'")
            return method(*args, **kwargs)
        finally:
            from .context import _request_context
            _request_context.reset(token)
            with self._lock:
                self._inflight -= 1

    # -- control plane ------------------------------------------------------
    def get_queue_len(self) -> int:
        return self._inflight

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"replica_tag": self.replica_tag,
                    "inflight": self._inflight,
                    "num_requests": self._num_requests,
                    "uptime_s": time.time() - self._start_time}

    def check_health(self) -> bool:
        fn = getattr(self._callable, "check_health", None)
        if callable(fn):
            fn()
        return True

    def reconfigure(self, user_config: Any) -> None:
        fn = getattr(self._callable, "reconfigure", None)
        if callable(fn):
            fn(user_config)

    def prepare_for_shutdown(self, timeout_s: float = 5.0) -> bool:
        """Drain in-flight requests — reference replica.py
        perform_graceful_shutdown."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                if self._inflight == 0:
                    break
            time.sleep(0.05)
        # Optional user shutdown hook; __del__ is left to GC so
        # non-idempotent destructors don't run twice.
        fn = getattr(self._callable, "shutdown", None)
        if callable(fn):
            try:
                fn()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                pass
        return True
