"""Core-runtime micro benchmarks — the ray_perf analog.

Measures the pure control plane (no jax anywhere): task and actor-call
latency/throughput, put/get across object sizes, a 10k-task queue drain,
and actor churn. Reference surface:
python/ray/_private/ray_perf.py:93-315 (micro-ops) and
release/benchmarks/distributed/test_many_tasks.py:111 (tasks_per_second
envelope). Numbers are NOT comparable 1:1 with the reference's C++
raylet — this runtime's conductor/worker plane is Python — which is
exactly why the envelope must be measured and published rather than
implied.

Run: `python -m ray_tpu._private.perf [--scale S] [--out FILE]`
Scale multiplies iteration counts (0.1 = smoke, 1.0 = full envelope).
Emits one JSON line per benchmark and an aggregate JSON file.
"""
from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from typing import Any, Callable, Dict, List

import numpy as np


def _latency_stats(samples_s: List[float]) -> Dict[str, float]:
    ms = sorted(s * 1e3 for s in samples_s)
    n = len(ms)
    return {
        "p50_ms": round(ms[n // 2], 3),
        "p99_ms": round(ms[min(n - 1, int(n * 0.99))], 3),
        "mean_ms": round(statistics.fmean(ms), 3),
    }


def _emit(rec: Dict[str, Any], sink: List[Dict[str, Any]]) -> None:
    sink.append(rec)
    print(json.dumps(rec), flush=True)


# -------------------------------------------------------------- benches

def bench_task_roundtrip(ray_tpu, sink, scale: float) -> None:
    """Submit → execute → get, one at a time (ray_perf 'single client
    tasks sync')."""
    @ray_tpu.remote
    def f():
        return b"ok"

    n = max(20, int(300 * scale))
    for _ in range(10):
        ray_tpu.get(f.remote())
    lat = []
    t0 = time.perf_counter()
    for _ in range(n):
        s = time.perf_counter()
        ray_tpu.get(f.remote())
        lat.append(time.perf_counter() - s)
    dt = time.perf_counter() - t0
    _emit({"name": "task_roundtrip_sync", "iters": n,
           "ops_per_s": round(n / dt, 1), **_latency_stats(lat)}, sink)


def bench_tasks_async(ray_tpu, sink, scale: float) -> None:
    """Pipelined submission, one batched get (ray_perf 'single client
    tasks async')."""
    @ray_tpu.remote
    def f():
        return b"ok"

    n = max(50, int(1000 * scale))
    # fully warm the worker pool: a cold pool amortizes process spawns
    # into the measurement and understates steady-state throughput
    ray_tpu.get([f.remote() for _ in range(max(50, n // 5))])
    t0 = time.perf_counter()
    ray_tpu.get([f.remote() for _ in range(n)], timeout=600.0)
    dt = time.perf_counter() - t0
    _emit({"name": "tasks_async", "iters": n,
           "ops_per_s": round(n / dt, 1)}, sink)


def bench_actor_calls(ray_tpu, sink, scale: float) -> None:
    """1:1 actor calls, sync latency and async throughput (ray_perf
    '1:1 actor calls sync/async')."""
    @ray_tpu.remote
    class A:
        def m(self):
            return b"ok"

    a = A.remote()
    ray_tpu.get(a.m.remote())

    n = max(20, int(300 * scale))
    lat = []
    t0 = time.perf_counter()
    for _ in range(n):
        s = time.perf_counter()
        ray_tpu.get(a.m.remote())
        lat.append(time.perf_counter() - s)
    dt = time.perf_counter() - t0
    _emit({"name": "actor_call_sync", "iters": n,
           "ops_per_s": round(n / dt, 1), **_latency_stats(lat)}, sink)

    n = max(50, int(1000 * scale))
    t0 = time.perf_counter()
    ray_tpu.get([a.m.remote() for _ in range(n)], timeout=600.0)
    dt = time.perf_counter() - t0
    _emit({"name": "actor_calls_async", "iters": n,
           "ops_per_s": round(n / dt, 1)}, sink)
    ray_tpu.kill(a)


def bench_put_get(ray_tpu, sink, scale: float) -> None:
    """put/get at 1KB / 1MB / 100MB (ray_perf put calls + put
    gigabytes). 100MB exercises the shm zero-copy path."""
    for label, nbytes, iters in (("1kb", 1 << 10, max(20, int(300 * scale))),
                                 ("1mb", 1 << 20, max(10, int(100 * scale))),
                                 ("100mb", 100 << 20, max(3, int(8 * scale)))):
        payload = np.random.default_rng(0).integers(
            0, 255, nbytes, dtype=np.uint8)
        ray_tpu.get(ray_tpu.put(payload))  # warm
        put_lat, get_lat, refs = [], [], []
        for _ in range(iters):
            s = time.perf_counter()
            r = ray_tpu.put(payload)
            put_lat.append(time.perf_counter() - s)
            refs.append(r)
        for r in refs:
            s = time.perf_counter()
            got = ray_tpu.get(r)
            get_lat.append(time.perf_counter() - s)
        assert got.nbytes == nbytes
        del refs
        # NB: get() of a locally-put object is a zero-copy store read, so
        # its "bandwidth" is a dict-lookup artifact — the cross-process
        # fetch is measured separately in bench_task_result_fetch.
        _emit({"name": f"put_{label}", "iters": iters,
               "ops_per_s": round(iters / sum(put_lat), 1),
               **_latency_stats(put_lat)}, sink)
        _emit({"name": f"get_local_{label}", "iters": iters,
               "ops_per_s": round(iters / sum(get_lat), 1),
               **_latency_stats(get_lat)}, sink)


def bench_task_result_fetch(ray_tpu, sink, scale: float) -> None:
    """get() of worker-produced results across process boundaries —
    1MB rides the RPC plane, 100MB the zero-copy shm slab (ray_perf
    'single client get calls' with real transfer)."""
    @ray_tpu.remote
    def make(nbytes):
        return np.zeros(nbytes, np.uint8)

    for label, nbytes, iters in (("1mb", 1 << 20, max(5, int(50 * scale))),
                                 ("100mb", 100 << 20, max(3, int(8 * scale)))):
        ray_tpu.get(make.remote(nbytes))  # warm
        lat = []
        for _ in range(iters):
            r = make.remote(nbytes)
            ray_tpu.wait([r], timeout=120.0)  # produced; time the fetch
            s = time.perf_counter()
            got = ray_tpu.get(r)
            lat.append(time.perf_counter() - s)
            assert got.nbytes == nbytes
            del got, r
        gbps = nbytes / statistics.fmean(lat) / 1e9
        _emit({"name": f"task_result_fetch_{label}", "iters": iters,
               "gb_per_s": round(gbps, 3), **_latency_stats(lat)}, sink)


def bench_queue_drain(ray_tpu, sink, scale: float) -> None:
    """Submit a deep queue of no-op tasks, then drain — the
    test_many_tasks.py:111 tasks_per_second shape at this runtime's
    scale (10k, not 1M: the conductor is Python and says so)."""
    @ray_tpu.remote
    def noop():
        return 0

    n = max(200, int(10_000 * scale))
    t0 = time.perf_counter()
    refs = [noop.remote() for _ in range(n)]
    t_submit = time.perf_counter() - t0
    ray_tpu.get(refs, timeout=1800.0)
    dt = time.perf_counter() - t0
    _emit({"name": "queue_drain", "iters": n,
           "submit_per_s": round(n / t_submit, 1),
           "tasks_per_s": round(n / dt, 1)}, sink)


def bench_actor_churn(ray_tpu, sink, scale: float) -> None:
    """Create → call → kill actors in bounded waves (release
    many_actors shape; each actor is a real worker process here)."""
    @ray_tpu.remote
    class Cell:
        def __init__(self, v):
            self.v = v

        def get(self):
            return self.v

    n = max(24, int(1000 * scale))
    wave = 8  # stay under the CPU resource cap while churning
    t0 = time.perf_counter()
    done = 0
    while done < n:
        k = min(wave, n - done)
        actors = [Cell.remote(i) for i in range(k)]
        got = ray_tpu.get([a.get.remote() for a in actors], timeout=120.0)
        assert got == list(range(k))
        for a in actors:
            ray_tpu.kill(a)
        done += k
    dt = time.perf_counter() - t0
    _emit({"name": "actor_churn", "iters": n,
           "actors_per_s": round(n / dt, 1)}, sink)


# fetch runs BEFORE put_get: the ~1GB of locally-pinned put payloads
# creates shm/page-cache pressure that would contaminate the fetch
# numbers (measured 20x degradation when ordered after)
BENCHES: List[Callable] = [
    bench_task_roundtrip, bench_tasks_async, bench_actor_calls,
    bench_task_result_fetch, bench_put_get, bench_queue_drain,
    bench_actor_churn,
]


def run(scale: float = 1.0, out: str = "") -> List[Dict[str, Any]]:
    import os

    import ray_tpu

    sink: List[Dict[str, Any]] = []
    ray_tpu.init(num_cpus=8)
    try:
        for bench in BENCHES:
            bench(ray_tpu, sink, scale)
    finally:
        ray_tpu.shutdown()
    if out:
        with open(out, "w") as f:
            # host_cpus contextualizes the numbers: on a 1-core host
            # every process timeshares one core, so pipelined throughput
            # cannot exceed serial by the usual margins
            json.dump({"scale": scale,
                       "host_cpus": os.cpu_count(),
                       "results": sink}, f, indent=1)
    return sink


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)
    run(scale=args.scale, out=args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
