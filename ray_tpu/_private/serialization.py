"""Serialization: cloudpickle for code/closures, out-of-band buffers for arrays.

Mirrors the responsibilities of the reference's
python/ray/_private/serialization.py (cloudpickle + pickle5 out-of-band
buffers + zero-copy numpy reads), but TPU-native: jax.Array leaves are
device_get'd to host numpy on serialize and can be re-placed on device by the
consumer; large numpy buffers are extracted out-of-band (pickle protocol 5) so
they can be placed in shared memory without a copy.
"""
from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle


def _default_reducer_override(obj):
    return NotImplemented


class _OOBPickler(cloudpickle.CloudPickler):
    """Cloudpickle with protocol-5 out-of-band buffer capture."""

    def __init__(self, file, buffers: List[pickle.PickleBuffer]):
        super().__init__(file, protocol=5, buffer_callback=buffers.append)


def serialize(obj: Any) -> Tuple[bytes, List[memoryview]]:
    """Serialize to (meta_bytes, raw_buffers).

    Buffers are raw memoryviews of large contiguous arrays (numpy etc.),
    suitable for placement in shared memory with no extra copy.
    """
    buffers: List[pickle.PickleBuffer] = []
    f = io.BytesIO()
    _OOBPickler(f, buffers).dump(obj)
    views = []
    for b in buffers:
        try:
            views.append(b.raw())
        except BufferError:
            # non-contiguous buffer: fall back to a contiguous copy
            import numpy as np

            views.append(memoryview(np.ascontiguousarray(b)).cast("B"))
    return f.getvalue(), views


def deserialize(meta: bytes, buffers: List[memoryview]) -> Any:
    return pickle.loads(meta, buffers=[pickle.PickleBuffer(b) for b in buffers])


def dumps(obj: Any) -> bytes:
    """In-band serialization (control plane messages, small payloads)."""
    return cloudpickle.dumps(obj, protocol=5)


def loads(data: bytes) -> Any:
    return pickle.loads(data)
