"""Per-node log monitor: tail worker log files, publish new lines.

Reference: python/ray/_private/log_monitor.py — a per-node daemon that
tails the session's worker logs and publishes them over GCS pubsub so
drivers can mirror task/actor prints to their own console
(log_to_driver). Here each node's monitor (conductor for the head,
node agent for worker hosts) tails `{session}/logs/worker-*.log` and
publishes batches on the conductor's `worker_logs` channel; drivers
subscribe through the existing pubsub fan-in and write to stderr with a
`(worker=… node=…)` prefix.
"""
from __future__ import annotations

import glob
import os
import threading
from typing import Callable, Dict, List, Optional

_MAX_LINE = 4096          # clip pathological lines
_MAX_LINES_PER_TICK = 500  # a log-spamming worker must not wedge pubsub


class LogMonitor:
    def __init__(self, logs_dir: str,
                 publish_fn: Callable[[List[Dict[str, str]]], None],
                 node_label: str = "", poll_s: float = 0.5):
        self.logs_dir = logs_dir
        self.publish_fn = publish_fn
        self.node_label = node_label
        self.poll_s = poll_s
        self._offsets: Dict[str, int] = {}
        self._partial: Dict[str, bytes] = {}   # unterminated trailing line
        self._backlog: Dict[str, List[bytes]] = {}  # cap-hit surplus lines
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "LogMonitor":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="log-monitor")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self.poll_s):
            try:
                batch = self.poll_once()
                if batch:
                    self.publish_fn(batch)
            except Exception:  # noqa: BLE001 — the tailer must survive
                pass

    def poll_once(self) -> List[Dict[str, str]]:
        """New complete lines since the last call, across all worker
        logs (bounded per tick)."""
        out: List[Dict[str, str]] = []
        for path in sorted(glob.glob(
                os.path.join(self.logs_dir, "worker-*.log"))):
            if len(out) >= _MAX_LINES_PER_TICK:
                break
            worker = os.path.basename(path)[len("worker-"):-len(".log")]
            lines = self._backlog.pop(path, None)
            if lines is None:
                # No retained surplus: read new bytes. While a backlog
                # exists we do NOT read — otherwise a log-spamming worker
                # grows the buffer without bound (each tick drains only
                # _MAX_LINES_PER_TICK but could read ~1MB more).
                try:
                    size = os.path.getsize(path)
                    offset = self._offsets.get(path, 0)
                    if size < offset:  # truncated/rotated: start over
                        offset = 0
                        self._partial.pop(path, None)
                    if size == offset:
                        continue
                    with open(path, "rb") as f:
                        f.seek(offset)
                        data = f.read(min(size - offset, 1 << 20))
                        self._offsets[path] = f.tell()
                except OSError:
                    continue
                data = self._partial.pop(path, b"") + data
                *lines, tail = data.split(b"\n")
                if tail:
                    self._partial[path] = tail
            for i, raw in enumerate(lines):
                if len(out) >= _MAX_LINES_PER_TICK:
                    # Cap hit inside an already-read chunk: the offset has
                    # advanced past these lines, so retain the surplus for
                    # the next tick instead of dropping it (bounded at one
                    # read's worth — see the no-read-while-backlog rule).
                    self._backlog[path] = lines[i:]
                    break
                line = raw[:_MAX_LINE].decode("utf-8", "replace").rstrip()
                if line:
                    out.append({"worker": worker, "node": self.node_label,
                                "line": line})
        return out


def format_log_line(entry: Dict[str, str]) -> str:
    """Driver-side rendering, reference `(pid=..., ip=...)` prefix."""
    node = entry.get("node") or ""
    src = f"worker={entry.get('worker', '?')}"
    if node:
        src += f", node={node}"
    return f"({src}) {entry.get('line', '')}"
