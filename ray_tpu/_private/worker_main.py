"""Worker process entrypoint — analog of the reference's
python/ray/_private/workers/default_worker.py (parse addresses, connect,
run the task loop :254,:289). Spawned by the conductor's worker pool."""
from __future__ import annotations

import os
import signal
import sys
import time

from ray_tpu.util import envknobs


def _bound_chips():
    """TPU chips this process was bound to at spawn (the conductor set
    TPU_VISIBLE_CHIPS); announced on every registration so a restarted
    conductor re-learns live bindings."""
    spec = os.environ.get("TPU_VISIBLE_CHIPS", "")
    try:
        chips = tuple(int(c) for c in spec.split(",") if c.strip() != "")
    except ValueError:
        return None
    return chips or None


def main() -> None:
    # Driver sys.path propagation: functions/classes pickled by reference
    # (module-level defs) must be importable here — the analog of the
    # reference's working_dir/py_modules runtime-env exposure.
    extra = os.environ.get("RAY_TPU_DRIVER_SYS_PATH", "")
    for p in reversed([p for p in extra.split(os.pathsep) if p]):
        if p not in sys.path:
            sys.path.insert(0, p)

    conductor = os.environ["RAY_TPU_CONDUCTOR"]
    worker_id = os.environ["RAY_TPU_WORKER_ID"]
    session_dir = os.environ.get("RAY_TPU_SESSION_DIR", "/tmp/ray_tpu")
    host, port = conductor.rsplit(":", 1)
    if os.environ.get("RAY_TPU_WORKER_VERBOSE") == "1":
        # boot diagnostics are opt-in: by default every worker's stdout
        # is mirrored to the driver (log_to_driver), and one boot line
        # per spawned process is pure noise interleaved into driver
        # output — failures surface through register_worker / the
        # conductor's death tracking, not this print
        print(f"[worker {worker_id[:8]}] connecting to conductor "
              f"{host}:{port}", flush=True)

    from . import worker as worker_mod
    from .worker import Worker

    w = Worker(mode="worker", conductor_address=(host, int(port)),
               session_dir=session_dir, worker_id=worker_id)
    worker_mod.global_worker = w
    # announce the chip binding so a restarted conductor (whose free_chips
    # reinitialized to the full range) re-learns which chips are taken
    chips = _bound_chips()
    w.conductor.call("register_worker", worker_id, w.address, os.getpid(),
                     os.environ.get("RAY_TPU_NODE_ID"), chips, timeout=30.0)

    def _term(signum, frame):
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)

    # Park the main thread; all work arrives via the RPC server. Re-register
    # periodically — idempotent, and it re-announces this worker to a
    # restarted conductor (persistence story; the reconnecting client
    # re-dials underneath). Exit only after a sustained outage: the
    # cluster is then really gone.
    from .config import config

    grace = config.worker_orphan_grace
    last_ok = time.monotonic()
    while True:
        # chunked: in fork-server children the kernel often delivers
        # SIGTERM to a non-main thread, which only sets CPython's signal
        # flag — the main thread notices at its next bytecode, so a flat
        # 5s sleep made teardown take seconds (cold-spawned processes
        # get the signal on the main thread and EINTR out immediately)
        for _ in range(50):
            time.sleep(0.1)
        try:
            ok = w.conductor.call(
                "register_worker", worker_id, w.address, os.getpid(),
                envknobs.get_str("RAY_TPU_NODE_ID"), chips, timeout=5.0)
            if ok is False:
                # conductor rebound our chips to another worker while we
                # were partitioned — we must not touch the TPU again
                os._exit(0)
            last_ok = time.monotonic()
        except Exception:
            if time.monotonic() - last_ok > grace:
                os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
