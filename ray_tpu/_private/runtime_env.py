"""Runtime environments — analog of the reference's
python/ray/_private/runtime_env/ (working_dir/py_modules packaging.py: zip
to GCS KV, URI-cached per node; env_vars; pip plugin pip.py; the plugin
protocol plugin.py) + the runtime-env agent flow
(agent/runtime_env_agent.py:161).

Built-in keys: env_vars, working_dir, py_modules, pip, uv, conda.
Directories are zipped, content-addressed, staged through the conductor
KV (the GCS-KV analog), and extracted once per worker into a hash-keyed
cache. `pip`/`uv` create a content-keyed venv (--system-site-packages,
--no-index: this runtime installs LOCAL wheels/dirs at env-setup time,
never from the network — TPU images are baked; `uv` uses the uv
installer when the binary exists, reference runtime_env/uv.py, and
falls back to pip otherwise). `conda` ACTIVATES an existing local env
by prefix or name (reference runtime_env/conda.py minus env creation —
same zero-egress stance). container/image_uri stay rejected: workers
come from a pre-started process pool on baked images, there is no
container runtime to launch them in. Third-party keys hook in via
register_plugin (reference plugin.py RuntimeEnvPlugin)."""
from __future__ import annotations

import contextlib
import hashlib
import io
import os
import shutil as _shutil
import subprocess
import sys
import tempfile
import zipfile
from typing import Any, Dict, List, Optional

_KV_NS = "runtime_env"
_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_UNSUPPORTED = ("container", "image_uri")
_BUILTIN = ("env_vars", "working_dir", "py_modules", "pip", "uv", "conda",
            "config")


class RuntimeEnvPlugin:
    """Extension point for custom runtime_env keys (reference
    python/ray/_private/runtime_env/plugin.py). Subclass, set `name`,
    and register_plugin() an instance; `validate` runs driver-side at
    submission, `apply` runs worker-side around execution and may mutate
    os.environ / sys.path (restored for non-permanent task envs by the
    surrounding context manager)."""

    name: str = ""

    def validate(self, value: Any) -> Any:
        return value

    def prepare(self, conductor, value: Any) -> Any:
        """Driver-side staging (e.g. upload); returns the wire value."""
        return value

    def apply(self, conductor, value: Any) -> None:
        """Worker-side activation before task/actor code runs."""


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}
_ENV_PLUGINS_LOADED: Optional[str] = None


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name or plugin.name in _BUILTIN:
        raise ValueError(f"invalid plugin name {plugin.name!r}")
    _PLUGINS[plugin.name] = plugin


def _plugins() -> Dict[str, RuntimeEnvPlugin]:
    """register_plugin()'d instances + classes named in
    RAY_TPU_RUNTIME_ENV_PLUGINS ("module:Class,module:Class") — the env
    var is how plugins reach WORKER processes, which never ran the
    driver's register_plugin call (reference RAY_RUNTIME_ENV_PLUGINS,
    runtime_env/plugin.py:40)."""
    global _ENV_PLUGINS_LOADED
    from ray_tpu.util import envknobs

    spec = envknobs.get_str("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    if spec and spec != _ENV_PLUGINS_LOADED:
        import importlib

        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            mod_name, _, cls_name = item.partition(":")
            plugin = getattr(importlib.import_module(mod_name), cls_name)()
            _PLUGINS.setdefault(plugin.name, plugin)
        _ENV_PLUGINS_LOADED = spec
    return _PLUGINS


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    env = dict(runtime_env)
    for key in _UNSUPPORTED:
        if key in env:
            raise ValueError(
                f"runtime_env[{key!r}] is not supported: workers come "
                "from a pre-started process pool on baked TPU images — "
                "there is no container runtime to launch them in; bake "
                "the image instead. Supported keys: env_vars, "
                "working_dir, py_modules, pip/uv (local wheels/dirs), "
                "conda (existing local env)")
    for key in env:
        if key not in _BUILTIN and key not in _plugins():
            raise ValueError(
                f"unknown runtime_env key {key!r}; built-ins: {_BUILTIN}, "
                f"registered plugins: {sorted(_PLUGINS)}")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
    if "pip" in env and "uv" in env:
        raise ValueError("runtime_env accepts 'pip' OR 'uv', not both "
                         "(they describe the same environment)")
    for installer in ("pip", "uv"):
        specs = env.get(installer)
        if specs is None or (isinstance(specs, dict) and "key" in specs):
            continue  # absent, or already prepared
        if not (isinstance(specs, list)
                and all(isinstance(s, str) for s in specs)):
            raise ValueError(
                f"runtime_env[{installer!r}] must be List[str] of local "
                "wheel/sdist/directory paths")
        for s in specs:
            if not (os.path.isfile(s) or os.path.isdir(s)):
                raise ValueError(
                    f"runtime_env[{installer!r}] entry {s!r} is not "
                    "supported: network installs at task time never "
                    "happen in ray_tpu (TPU images are baked; zero "
                    "egress) — pass a local wheel/sdist/directory path "
                    "instead")
    conda = env.get("conda")
    if conda is not None:
        if isinstance(conda, dict) and ("dependencies" in conda
                                        or "channels" in conda):
            raise ValueError(
                "runtime_env['conda'] with an environment spec "
                "(dependencies/channels) is not supported: ray_tpu never "
                "creates envs from the network at task time — pass the "
                "NAME or PREFIX PATH of an env that already exists on "
                "the workers")
        if isinstance(conda, dict):
            if not (conda.get("prefix") or conda.get("name")):
                raise ValueError("runtime_env['conda'] dict needs "
                                 "'prefix' or 'name'")
        elif not isinstance(conda, str):
            raise ValueError("runtime_env['conda'] must be an env name, "
                             "a prefix path, or {'prefix'|'name': ...}")
    for key, plugin in _plugins().items():
        if key in env:
            env[key] = plugin.validate(env[key])
    return env


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for f in files:
                if f.endswith(".pyc"):
                    continue
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES}); exclude large data files")
    return data


def package_dir(conductor, path: str) -> str:
    """Zip + upload a directory to the conductor KV; returns a
    content-addressed URI (reference packaging.py upload_package_if_needed).
    """
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory {path!r} does not exist")
    data = _zip_dir(path)
    digest = hashlib.sha256(data).hexdigest()[:24]
    uri = f"kv://{digest}.zip"
    key = uri.encode()
    if conductor.call("kv_get", key, _KV_NS, timeout=30.0) is None:
        conductor.call("kv_put", key, data, True, _KV_NS, timeout=60.0)
    return uri


def package_file(conductor, path: str) -> str:
    """Upload one artifact (wheel/sdist) to the KV, content-addressed."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(f"runtime_env artifact {path} too large")
    digest = hashlib.sha256(data).hexdigest()[:24]
    uri = f"kv://{digest}.bin"
    key = uri.encode()
    if conductor.call("kv_get", key, _KV_NS, timeout=30.0) is None:
        conductor.call("kv_put", key, data, True, _KV_NS, timeout=60.0)
    return uri


def _prepare_pip(conductor, specs: List[str]) -> Dict[str, Any]:
    """Stage local artifacts so remote workers can install them offline
    (reference pip.py + packaging.py upload flow)."""
    staged = []
    for s in specs:
        if os.path.isfile(s):  # wheel/sdist: filename carries pip's tags
            staged.append({"kind": "file", "uri": package_file(conductor, s),
                           "filename": os.path.basename(s)})
        elif os.path.isdir(s):
            staged.append({"kind": "dir", "uri": package_dir(conductor, s)})
        else:  # validate() rejected bare requirements before this point
            raise ValueError(f"runtime_env['pip'] entry {s!r} vanished "
                             "between validation and staging")
    key = hashlib.sha256(repr(staged).encode()).hexdigest()[:24]
    return {"key": key, "specs": staged}


def prepare(conductor, runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Driver-side: replace local dirs/artifacts with uploaded URIs.
    Idempotent."""
    env = validate(runtime_env)
    if not env:
        return {}
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not wd.startswith("kv://"):
        out["working_dir"] = package_dir(conductor, wd)
    mods = []
    for m in env.get("py_modules") or []:
        mods.append(m if m.startswith("kv://")
                    else package_dir(conductor, m))
    if mods:
        out["py_modules"] = mods
    for installer in ("pip", "uv"):
        specs = env.get(installer)
        if specs and not (isinstance(specs, dict) and "key" in specs):
            out[installer] = _prepare_pip(conductor, specs)
    for key, plugin in _plugins().items():
        if key in env:
            out[key] = plugin.prepare(conductor, env[key])
    return out


def _cache_root() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "runtime_env")


def ensure_local(conductor, uri: str) -> str:
    """Worker-side: fetch + extract a kv:// package once; returns its
    directory (reference uri_cache.py — content-addressed, shared across
    tasks on the worker)."""
    digest = uri[len("kv://"):-len(".zip")]
    dest = os.path.join(_cache_root(), digest)
    if os.path.isdir(dest):
        return dest
    data = conductor.call("kv_get", uri.encode(), _KV_NS, timeout=60.0)
    if data is None:
        raise RuntimeError(f"runtime_env package {uri} not found in KV")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:  # another worker won the race
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def ensure_pip_env(conductor, prepared: Dict[str, Any],
                   installer: str = "pip") -> str:
    """Worker-side: materialize the staged pip/uv env once; returns its
    site-packages dir. A content-keyed venv (--system-site-packages so
    the baked jax stack stays visible; --no-index so nothing touches the
    network) mirrors the reference's per-env virtualenv (pip.py:282) —
    shared by every task/actor with the same spec on this machine.
    installer='uv' uses the uv binary when present (reference uv.py's
    faster installs) and falls back to pip — the resulting env is
    identical either way."""
    key = prepared["key"]
    venv_dir = os.path.join(_cache_root(), "venvs", key)
    ok_marker = os.path.join(venv_dir, ".ray_tpu_ok")
    lib = os.path.join(venv_dir, "lib",
                       f"python{sys.version_info.major}."
                       f"{sys.version_info.minor}", "site-packages")
    if os.path.exists(ok_marker):
        return lib
    # localize staged artifacts
    art_dir = os.path.join(_cache_root(), "artifacts", key)
    os.makedirs(art_dir, exist_ok=True)
    targets: List[str] = []
    for s in prepared["specs"]:
        if s["kind"] == "file":
            dest = os.path.join(art_dir, s["filename"])
            if not os.path.exists(dest):
                data = conductor.call("kv_get", s["uri"].encode(), _KV_NS,
                                      timeout=60.0)
                if data is None:
                    raise RuntimeError(f"pip artifact {s['uri']} lost")
                with open(dest + ".tmp", "wb") as f:
                    f.write(data)
                os.replace(dest + ".tmp", dest)
            targets.append(dest)
        elif s["kind"] == "dir":
            targets.append(ensure_local(conductor, s["uri"]))
        else:
            targets.append(s["spec"])
    uv = _shutil.which("uv") if installer == "uv" else None
    if uv:
        subprocess.run([uv, "venv", "--system-site-packages",
                        "--python", sys.executable, venv_dir],
                       check=True, capture_output=True)
        cmd = [uv, "pip", "install", "--quiet", "--no-index",
               "--python", os.path.join(venv_dir, "bin", "python"),
               *targets]
    else:
        subprocess.run([sys.executable, "-m", "venv",
                        "--system-site-packages", venv_dir],
                       check=True, capture_output=True)
        cmd = [os.path.join(venv_dir, "bin", "pip"), "install", "--quiet",
               "--no-index", "--no-build-isolation", *targets]
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(
            f"{installer} runtime_env failed (offline install of "
            f"{targets}): {r.stdout}\n{r.stderr}")
    with open(ok_marker, "w") as f:
        f.write("ok")
    return lib


def resolve_conda_prefix(value: Any) -> str:
    """Locate an EXISTING local conda env (reference conda.py
    get_conda_env_dir — minus creation). Accepts a prefix path directly;
    names are searched in CONDA_ENVS_PATH, the active conda install's
    envs/ dir, and the conventional roots."""
    from .. import exceptions as exc

    if isinstance(value, dict):
        value = value.get("prefix") or value.get("name")
    value = str(value)
    if os.path.sep in value or os.path.isdir(value):
        prefix = os.path.abspath(os.path.expanduser(value))
        if os.path.exists(os.path.join(prefix, "bin", "python")):
            return prefix
        raise exc.RuntimeEnvSetupError(
            f"runtime_env['conda'] prefix {value!r} has no bin/python — "
            "not a conda env (ray_tpu never creates envs at task time; "
            "create it beforehand)")
    roots: List[str] = []
    for d in os.environ.get("CONDA_ENVS_PATH", "").split(os.pathsep):
        if d:
            roots.append(d)
    conda_exe = os.environ.get("CONDA_EXE") or _shutil.which("conda")
    if conda_exe:
        roots.append(os.path.join(
            os.path.dirname(os.path.dirname(conda_exe)), "envs"))
    for base in ("~/miniconda3", "~/anaconda3", "/opt/conda"):
        roots.append(os.path.join(os.path.expanduser(base), "envs"))
    for root in roots:
        prefix = os.path.join(root, value)
        if os.path.exists(os.path.join(prefix, "bin", "python")):
            return prefix
    raise exc.RuntimeEnvSetupError(
        f"runtime_env['conda'] env {value!r} not found on this worker "
        f"(searched {roots}); ray_tpu activates EXISTING envs only — "
        "create it on every node beforehand (baked images, zero egress)")


def _apply_conda(value: Any) -> Dict[str, Optional[str]]:
    """Activate an existing conda env for this process: PATH, CONDA_*
    env vars, and its site-packages at the front of sys.path. Returns
    {env_var: previous} so a task-scoped application can be undone."""
    prefix = resolve_conda_prefix(value)
    saved: Dict[str, Optional[str]] = {
        "PATH": os.environ.get("PATH"),
        "CONDA_PREFIX": os.environ.get("CONDA_PREFIX"),
        "CONDA_DEFAULT_ENV": os.environ.get("CONDA_DEFAULT_ENV"),
    }
    os.environ["PATH"] = (os.path.join(prefix, "bin") + os.pathsep
                          + os.environ.get("PATH", ""))
    os.environ["CONDA_PREFIX"] = prefix
    os.environ["CONDA_DEFAULT_ENV"] = os.path.basename(prefix)
    lib = os.path.join(prefix, "lib")
    if os.path.isdir(lib):
        for entry in sorted(os.listdir(lib)):
            sp = os.path.join(lib, entry, "site-packages")
            if entry.startswith("python") and os.path.isdir(sp):
                if sp not in sys.path:
                    sys.path.insert(0, sp)
                break
    return saved


@contextlib.contextmanager
def applied(conductor, runtime_env: Optional[Dict[str, Any]],
            permanent: bool = False):
    """Apply a (prepared) runtime_env around execution. For tasks the
    previous env/cwd/sys.path are restored afterwards (shared worker);
    actors pass permanent=True (dedicated process, reference behavior)."""
    env = runtime_env or {}
    if not env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = env.get("working_dir")
        if wd:
            local = ensure_local(conductor, wd)
            os.chdir(local)
            if local not in sys.path:
                sys.path.insert(0, local)
        for uri in env.get("py_modules") or []:
            local = ensure_local(conductor, uri)
            if local not in sys.path:
                sys.path.insert(0, local)
        for installer in ("pip", "uv"):
            specs = env.get(installer)
            if specs:
                sp = ensure_pip_env(conductor, specs, installer=installer)
                if sp not in sys.path:
                    sys.path.insert(0, sp)
        conda = env.get("conda")
        if conda:
            for var, old in _apply_conda(conda).items():
                saved_env.setdefault(var, old)
        for key, plugin in _plugins().items():
            if key in env:
                plugin.apply(conductor, env[key])
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
