"""Runtime environments — analog of the reference's
python/ray/_private/runtime_env/ (working_dir/py_modules packaging.py: zip
to GCS KV, URI-cached per node; env_vars; pip plugin pip.py; the plugin
protocol plugin.py) + the runtime-env agent flow
(agent/runtime_env_agent.py:161).

Built-in keys: env_vars, working_dir, py_modules, pip. Directories are
zipped, content-addressed, staged through the conductor KV (the GCS-KV
analog), and extracted once per worker into a hash-keyed cache. `pip`
creates a content-keyed venv (--system-site-packages, --no-index: this
runtime installs LOCAL wheels/dirs at env-setup time, never from the
network — TPU images are baked) whose site-packages is prepended for the
task/actor. conda/container stay rejected; third-party keys can hook in
via register_plugin (reference plugin.py RuntimeEnvPlugin)."""
from __future__ import annotations

import contextlib
import hashlib
import io
import os
import subprocess
import sys
import tempfile
import zipfile
from typing import Any, Dict, List, Optional

_KV_NS = "runtime_env"
_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_UNSUPPORTED = ("conda", "container", "uv", "image_uri")
_BUILTIN = ("env_vars", "working_dir", "py_modules", "pip", "config")


class RuntimeEnvPlugin:
    """Extension point for custom runtime_env keys (reference
    python/ray/_private/runtime_env/plugin.py). Subclass, set `name`,
    and register_plugin() an instance; `validate` runs driver-side at
    submission, `apply` runs worker-side around execution and may mutate
    os.environ / sys.path (restored for non-permanent task envs by the
    surrounding context manager)."""

    name: str = ""

    def validate(self, value: Any) -> Any:
        return value

    def prepare(self, conductor, value: Any) -> Any:
        """Driver-side staging (e.g. upload); returns the wire value."""
        return value

    def apply(self, conductor, value: Any) -> None:
        """Worker-side activation before task/actor code runs."""


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}
_ENV_PLUGINS_LOADED: Optional[str] = None


def register_plugin(plugin: RuntimeEnvPlugin) -> None:
    if not plugin.name or plugin.name in _BUILTIN:
        raise ValueError(f"invalid plugin name {plugin.name!r}")
    _PLUGINS[plugin.name] = plugin


def _plugins() -> Dict[str, RuntimeEnvPlugin]:
    """register_plugin()'d instances + classes named in
    RAY_TPU_RUNTIME_ENV_PLUGINS ("module:Class,module:Class") — the env
    var is how plugins reach WORKER processes, which never ran the
    driver's register_plugin call (reference RAY_RUNTIME_ENV_PLUGINS,
    runtime_env/plugin.py:40)."""
    global _ENV_PLUGINS_LOADED
    spec = os.environ.get("RAY_TPU_RUNTIME_ENV_PLUGINS", "")
    if spec and spec != _ENV_PLUGINS_LOADED:
        import importlib

        for item in spec.split(","):
            item = item.strip()
            if not item:
                continue
            mod_name, _, cls_name = item.partition(":")
            plugin = getattr(importlib.import_module(mod_name), cls_name)()
            _PLUGINS.setdefault(plugin.name, plugin)
        _ENV_PLUGINS_LOADED = spec
    return _PLUGINS


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    env = dict(runtime_env)
    for key in _UNSUPPORTED:
        if key in env:
            raise ValueError(
                f"runtime_env[{key!r}] is not supported: ray_tpu never "
                "builds images/envs from the network at task time (bake "
                "them into the image); supported keys: env_vars, "
                "working_dir, py_modules, pip (local wheels/dirs)")
    for key in env:
        if key not in _BUILTIN and key not in _plugins():
            raise ValueError(
                f"unknown runtime_env key {key!r}; built-ins: {_BUILTIN}, "
                f"registered plugins: {sorted(_PLUGINS)}")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
    pip = env.get("pip")
    if pip is not None:
        if not (isinstance(pip, list)
                and all(isinstance(s, str) for s in pip)):
            raise ValueError("runtime_env['pip'] must be List[str] of local "
                             "wheel/sdist/directory paths")
        for s in pip:
            if not (os.path.isfile(s) or os.path.isdir(s)):
                raise ValueError(
                    f"runtime_env['pip'] entry {s!r} is not supported: "
                    "network installs at task time never happen in "
                    "ray_tpu (TPU images are baked; zero egress) — pass "
                    "a local wheel/sdist/directory path instead")
    for key, plugin in _plugins().items():
        if key in env:
            env[key] = plugin.validate(env[key])
    return env


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for f in files:
                if f.endswith(".pyc"):
                    continue
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES}); exclude large data files")
    return data


def package_dir(conductor, path: str) -> str:
    """Zip + upload a directory to the conductor KV; returns a
    content-addressed URI (reference packaging.py upload_package_if_needed).
    """
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory {path!r} does not exist")
    data = _zip_dir(path)
    digest = hashlib.sha256(data).hexdigest()[:24]
    uri = f"kv://{digest}.zip"
    key = uri.encode()
    if conductor.call("kv_get", key, _KV_NS, timeout=30.0) is None:
        conductor.call("kv_put", key, data, True, _KV_NS, timeout=60.0)
    return uri


def package_file(conductor, path: str) -> str:
    """Upload one artifact (wheel/sdist) to the KV, content-addressed."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(f"runtime_env artifact {path} too large")
    digest = hashlib.sha256(data).hexdigest()[:24]
    uri = f"kv://{digest}.bin"
    key = uri.encode()
    if conductor.call("kv_get", key, _KV_NS, timeout=30.0) is None:
        conductor.call("kv_put", key, data, True, _KV_NS, timeout=60.0)
    return uri


def _prepare_pip(conductor, specs: List[str]) -> Dict[str, Any]:
    """Stage local artifacts so remote workers can install them offline
    (reference pip.py + packaging.py upload flow)."""
    staged = []
    for s in specs:
        if os.path.isfile(s):  # wheel/sdist: filename carries pip's tags
            staged.append({"kind": "file", "uri": package_file(conductor, s),
                           "filename": os.path.basename(s)})
        elif os.path.isdir(s):
            staged.append({"kind": "dir", "uri": package_dir(conductor, s)})
        else:  # validate() rejected bare requirements before this point
            raise ValueError(f"runtime_env['pip'] entry {s!r} vanished "
                             "between validation and staging")
    key = hashlib.sha256(repr(staged).encode()).hexdigest()[:24]
    return {"key": key, "specs": staged}


def prepare(conductor, runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Driver-side: replace local dirs/artifacts with uploaded URIs.
    Idempotent."""
    env = validate(runtime_env)
    if not env:
        return {}
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not wd.startswith("kv://"):
        out["working_dir"] = package_dir(conductor, wd)
    mods = []
    for m in env.get("py_modules") or []:
        mods.append(m if m.startswith("kv://")
                    else package_dir(conductor, m))
    if mods:
        out["py_modules"] = mods
    pip = env.get("pip")
    if pip and not (isinstance(pip, dict) and "key" in pip):
        out["pip"] = _prepare_pip(conductor, pip)
    for key, plugin in _plugins().items():
        if key in env:
            out[key] = plugin.prepare(conductor, env[key])
    return out


def _cache_root() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "runtime_env")


def ensure_local(conductor, uri: str) -> str:
    """Worker-side: fetch + extract a kv:// package once; returns its
    directory (reference uri_cache.py — content-addressed, shared across
    tasks on the worker)."""
    digest = uri[len("kv://"):-len(".zip")]
    dest = os.path.join(_cache_root(), digest)
    if os.path.isdir(dest):
        return dest
    data = conductor.call("kv_get", uri.encode(), _KV_NS, timeout=60.0)
    if data is None:
        raise RuntimeError(f"runtime_env package {uri} not found in KV")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:  # another worker won the race
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


def ensure_pip_env(conductor, prepared: Dict[str, Any]) -> str:
    """Worker-side: materialize the staged pip env once; returns its
    site-packages dir. A content-keyed venv (--system-site-packages so
    the baked jax stack stays visible; --no-index so nothing touches the
    network) mirrors the reference's per-env virtualenv (pip.py:282) —
    shared by every task/actor with the same spec on this machine."""
    key = prepared["key"]
    venv_dir = os.path.join(_cache_root(), "venvs", key)
    ok_marker = os.path.join(venv_dir, ".ray_tpu_ok")
    lib = os.path.join(venv_dir, "lib",
                       f"python{sys.version_info.major}."
                       f"{sys.version_info.minor}", "site-packages")
    if os.path.exists(ok_marker):
        return lib
    # localize staged artifacts
    art_dir = os.path.join(_cache_root(), "artifacts", key)
    os.makedirs(art_dir, exist_ok=True)
    targets: List[str] = []
    for s in prepared["specs"]:
        if s["kind"] == "file":
            dest = os.path.join(art_dir, s["filename"])
            if not os.path.exists(dest):
                data = conductor.call("kv_get", s["uri"].encode(), _KV_NS,
                                      timeout=60.0)
                if data is None:
                    raise RuntimeError(f"pip artifact {s['uri']} lost")
                with open(dest + ".tmp", "wb") as f:
                    f.write(data)
                os.replace(dest + ".tmp", dest)
            targets.append(dest)
        elif s["kind"] == "dir":
            targets.append(ensure_local(conductor, s["uri"]))
        else:
            targets.append(s["spec"])
    subprocess.run([sys.executable, "-m", "venv", "--system-site-packages",
                    venv_dir], check=True, capture_output=True)
    pip = os.path.join(venv_dir, "bin", "pip")
    r = subprocess.run(
        [pip, "install", "--quiet", "--no-index",
         "--no-build-isolation", *targets],
        capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(
            f"pip runtime_env failed (offline install of {targets}): "
            f"{r.stdout}\n{r.stderr}")
    with open(ok_marker, "w") as f:
        f.write("ok")
    return lib


@contextlib.contextmanager
def applied(conductor, runtime_env: Optional[Dict[str, Any]],
            permanent: bool = False):
    """Apply a (prepared) runtime_env around execution. For tasks the
    previous env/cwd/sys.path are restored afterwards (shared worker);
    actors pass permanent=True (dedicated process, reference behavior)."""
    env = runtime_env or {}
    if not env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = env.get("working_dir")
        if wd:
            local = ensure_local(conductor, wd)
            os.chdir(local)
            if local not in sys.path:
                sys.path.insert(0, local)
        for uri in env.get("py_modules") or []:
            local = ensure_local(conductor, uri)
            if local not in sys.path:
                sys.path.insert(0, local)
        pip = env.get("pip")
        if pip:
            sp = ensure_pip_env(conductor, pip)
            if sp not in sys.path:
                sys.path.insert(0, sp)
        for key, plugin in _plugins().items():
            if key in env:
                plugin.apply(conductor, env[key])
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
