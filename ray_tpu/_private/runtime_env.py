"""Runtime environments — analog of the reference's
python/ray/_private/runtime_env/ (working_dir/py_modules packaging.py: zip
to GCS KV, URI-cached per node; env_vars; plugins) + the runtime-env agent
flow (agent/runtime_env_agent.py:161).

Scope for the TPU build: env_vars, working_dir, py_modules, and config
validation. Directories are zipped, content-addressed, staged through the
conductor KV (the GCS-KV analog), and extracted once per worker into a
hash-keyed cache. pip/conda/container are rejected with a clear error —
this runtime never installs packages at task time (TPU images are baked;
the reference's conda path is its slowest, least reproducible feature)."""
from __future__ import annotations

import contextlib
import hashlib
import io
import os
import sys
import tempfile
import zipfile
from typing import Any, Dict, Optional

_KV_NS = "runtime_env"
_MAX_PACKAGE_BYTES = 256 * 1024 * 1024
_UNSUPPORTED = ("pip", "conda", "container", "uv", "image_uri")


def validate(runtime_env: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    if not runtime_env:
        return {}
    env = dict(runtime_env)
    for key in _UNSUPPORTED:
        if key in env:
            raise ValueError(
                f"runtime_env[{key!r}] is not supported: ray_tpu never "
                "installs packages at task time (bake them into the image); "
                "supported keys: env_vars, working_dir, py_modules")
    ev = env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("runtime_env['env_vars'] must be Dict[str, str]")
    return env


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    base = os.path.abspath(path)
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(base):
            dirs[:] = [d for d in dirs
                       if d not in ("__pycache__", ".git", ".venv")]
            for f in files:
                if f.endswith(".pyc"):
                    continue
                full = os.path.join(root, f)
                z.write(full, os.path.relpath(full, base))
    data = buf.getvalue()
    if len(data) > _MAX_PACKAGE_BYTES:
        raise ValueError(
            f"runtime_env package {path} is {len(data)} bytes "
            f"(limit {_MAX_PACKAGE_BYTES}); exclude large data files")
    return data


def package_dir(conductor, path: str) -> str:
    """Zip + upload a directory to the conductor KV; returns a
    content-addressed URI (reference packaging.py upload_package_if_needed).
    """
    if not os.path.isdir(path):
        raise ValueError(f"runtime_env directory {path!r} does not exist")
    data = _zip_dir(path)
    digest = hashlib.sha256(data).hexdigest()[:24]
    uri = f"kv://{digest}.zip"
    key = uri.encode()
    if conductor.call("kv_get", key, _KV_NS, timeout=30.0) is None:
        conductor.call("kv_put", key, data, True, _KV_NS, timeout=60.0)
    return uri


def prepare(conductor, runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Driver-side: replace local dirs with uploaded URIs. Idempotent."""
    env = validate(runtime_env)
    if not env:
        return {}
    out = dict(env)
    wd = env.get("working_dir")
    if wd and not wd.startswith("kv://"):
        out["working_dir"] = package_dir(conductor, wd)
    mods = []
    for m in env.get("py_modules") or []:
        mods.append(m if m.startswith("kv://")
                    else package_dir(conductor, m))
    if mods:
        out["py_modules"] = mods
    return out


def _cache_root() -> str:
    return os.path.join(tempfile.gettempdir(), "ray_tpu", "runtime_env")


def ensure_local(conductor, uri: str) -> str:
    """Worker-side: fetch + extract a kv:// package once; returns its
    directory (reference uri_cache.py — content-addressed, shared across
    tasks on the worker)."""
    digest = uri[len("kv://"):-len(".zip")]
    dest = os.path.join(_cache_root(), digest)
    if os.path.isdir(dest):
        return dest
    data = conductor.call("kv_get", uri.encode(), _KV_NS, timeout=60.0)
    if data is None:
        raise RuntimeError(f"runtime_env package {uri} not found in KV")
    tmp = dest + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
    try:
        os.replace(tmp, dest)
    except OSError:  # another worker won the race
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


@contextlib.contextmanager
def applied(conductor, runtime_env: Optional[Dict[str, Any]],
            permanent: bool = False):
    """Apply a (prepared) runtime_env around execution. For tasks the
    previous env/cwd/sys.path are restored afterwards (shared worker);
    actors pass permanent=True (dedicated process, reference behavior)."""
    env = runtime_env or {}
    if not env:
        yield
        return
    saved_env: Dict[str, Optional[str]] = {}
    saved_cwd = os.getcwd()
    saved_path = list(sys.path)
    try:
        for k, v in (env.get("env_vars") or {}).items():
            saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        wd = env.get("working_dir")
        if wd:
            local = ensure_local(conductor, wd)
            os.chdir(local)
            if local not in sys.path:
                sys.path.insert(0, local)
        for uri in env.get("py_modules") or []:
            local = ensure_local(conductor, uri)
            if local not in sys.path:
                sys.path.insert(0, local)
        yield
    finally:
        if not permanent:
            for k, old in saved_env.items():
                if old is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = old
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path
