"""Identifiers for objects, tasks, actors, jobs, nodes, placement groups.

TPU-native analog of the reference's binary ID scheme
(/root/reference/src/ray/common/id.h). We keep the same *semantic* structure —
IDs embed ownership/lineage hints — but use a simple 16-byte random payload plus
a type tag instead of the reference's bit-packed lineage indices: lineage lives
in the owner's TaskManager table instead (see task_manager.py).
"""
from __future__ import annotations

import os
import threading

_counter_lock = threading.Lock()
_counter = 0


def _rand_hex(n: int = 16) -> str:
    return os.urandom(n).hex()


class BaseID:
    __slots__ = ("_hex",)
    _prefix = "id"

    def __init__(self, hex_id: str | None = None):
        self._hex = hex_id if hex_id is not None else _rand_hex()

    @classmethod
    def from_hex(cls, hex_id: str) -> "BaseID":
        return cls(hex_id)

    def hex(self) -> str:
        return self._hex

    def binary(self) -> bytes:
        return bytes.fromhex(self._hex)

    def __hash__(self):
        return hash((self._prefix, self._hex))

    def __eq__(self, other):
        return type(other) is type(self) and other._hex == self._hex

    def __repr__(self):
        return f"{type(self).__name__}({self._hex[:12]}…)"

    def __reduce__(self):
        return (type(self), (self._hex,))


class JobID(BaseID):
    _prefix = "job"


class NodeID(BaseID):
    _prefix = "node"


class TaskID(BaseID):
    _prefix = "task"


class ActorID(BaseID):
    _prefix = "actor"


class ObjectID(BaseID):
    _prefix = "object"


class PlacementGroupID(BaseID):
    _prefix = "pg"


class WorkerID(BaseID):
    _prefix = "worker"


def next_seqno() -> int:
    """Process-wide monotonically increasing sequence number."""
    global _counter
    with _counter_lock:
        _counter += 1
        return _counter
