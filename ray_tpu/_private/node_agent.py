"""Per-host node agent: the raylet-equivalent daemon for worker hosts.

A NodeAgent joins an existing cluster (`python -m ray_tpu start
--address head:port`), registers its host's resources with the conductor,
and owns that host's worker processes: the conductor's scheduler asks the
agent to spawn workers when a lease lands on this node, and the agent's
heartbeat reports worker deaths (the conductor cannot poll remote pids).

Reference: src/ray/raylet/node_manager.h:125 (per-node daemon owning the
WorkerPool), src/ray/gcs/gcs_server/gcs_health_check_manager.cc (the
health channel this replaces with push heartbeats).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .ids import NodeID, WorkerID
from .rpc import ReconnectingClient, RpcServer
from .worker_spawn import spawn_worker_process

def _heartbeat_period() -> float:
    from .config import config

    return config.node_heartbeat


class NodeAgentHandler:
    """RPC handler — conductor-facing surface of one worker host."""

    def __init__(self, node_id: str, conductor_address: Tuple[str, int],
                 session_dir: str,
                 worker_env: Optional[Dict[str, str]] = None):
        self.node_id = node_id
        self.conductor_address = tuple(conductor_address)
        self.session_dir = session_dir
        self.worker_env = dict(worker_env or {})
        self._procs: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def spawn_worker(self, worker_id: str,
                     env_extra: Optional[Dict[str, str]] = None) -> bool:
        proc = spawn_worker_process(
            worker_id, self.conductor_address, self.session_dir,
            worker_env=self.worker_env, env_extra=env_extra,
            node_id=self.node_id)
        with self._lock:
            self._procs[worker_id] = proc
        return True

    def reap_dead(self) -> List[str]:
        """Worker ids whose processes exited since the last call."""
        dead = []
        with self._lock:
            for wid, proc in list(self._procs.items()):
                if proc.poll() is not None:
                    dead.append(wid)
                    del self._procs[wid]
        return dead

    def oom_tick(self, mon) -> Optional[Tuple[str, str]]:
        """One memory-monitor tick over this host's workers. The agent
        has no task/actor state, so the victim is purely highest-RSS."""
        with self._lock:
            cands = [(wid, p.pid, "BUSY")
                     for wid, p in self._procs.items() if p.poll() is None]
        return mon.kill_greediest(cands, self.node_id[:12])

    def ping(self) -> str:
        return "pong"

    def stop_node(self) -> bool:
        self._shutdown_workers()
        return True

    def _shutdown_workers(self) -> None:
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass
        deadline = time.monotonic() + 3.0
        for p in procs:
            try:
                p.wait(max(0.0, deadline - time.monotonic()))
            except Exception:
                try:
                    p.kill()
                except OSError:
                    pass


class NodeAgent:
    """Runs a NodeAgentHandler on an RpcServer, registers with the
    conductor, and heartbeats (carrying dead-worker reports)."""

    def __init__(self, conductor_address: Tuple[str, int],
                 resources: Dict[str, float],
                 session_dir: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 worker_env: Optional[Dict[str, str]] = None,
                 node_id: Optional[str] = None):
        self.node_id = node_id or NodeID().hex()
        self.resources = dict(resources)
        self.conductor_address = tuple(conductor_address)
        self._conductor = ReconnectingClient(self.conductor_address)
        info = self._conductor.call("session_info", timeout=10.0)
        if session_dir is None:
            session_dir = info["session_dir"]
        self.session_dir = session_dir
        self._conductor_machine = info.get("machine")
        self.handler = NodeAgentHandler(self.node_id,
                                        self.conductor_address,
                                        session_dir, worker_env=worker_env)
        self.server = RpcServer(self.handler, host=host, port=port,
                                max_workers=8)
        self._stopped = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, name="node-agent-heartbeat",
            daemon=True)
        # OOM causes awaiting a successful heartbeat ack — a dropped
        # heartbeat (or conductor restart) must not lose the diagnosis
        self._pending_causes: Dict[str, str] = {}
        self._causes_lock = threading.Lock()
        self._mem_thread = threading.Thread(
            target=self._memory_loop, name="node-agent-memmon", daemon=True)

    def start(self) -> "NodeAgent":
        self.server.start()
        self._conductor.call("register_node", self.node_id, self.resources,
                             self.server.address, timeout=10.0)
        self._hb_thread.start()
        self._mem_thread.start()
        # preemption watcher: the maintenance-event channel
        # (RAY_TPU_MAINTENANCE_EVENT file) turns an upcoming host
        # reclaim into a conductor broadcast — "checkpoint now, grace N
        # seconds" — before the platform starts killing processes
        self._preemption_watcher = None
        from ray_tpu.resilience.preemption import (ENV_VAR,
                                                   PreemptionWatcher)

        if os.environ.get(ENV_VAR):
            self._preemption_watcher = PreemptionWatcher(
                self.notify_preemption).start()
        # tail THIS host's worker logs into the worker_logs channel — but
        # only when the head is a different machine: on a shared host the
        # conductor's own tailer already covers the shared session dir
        # (reference: one log_monitor per node)
        from .worker import _MACHINE_ID

        if self._conductor_machine != _MACHINE_ID:
            from .log_monitor import LogMonitor

            self._log_monitor = LogMonitor(
                os.path.join(self.session_dir, "logs"),
                lambda batch: self._conductor.notify(
                    "publish", "worker_logs", batch),
                node_label=self.node_id[:12]).start()
        return self

    def _memory_loop(self) -> None:
        """Memory monitor at its OWN cadence (memory_monitor_refresh_ms)
        — the heartbeat period may be seconds, far too slow to beat the
        kernel OOM killer to a runaway task."""
        from .config import config
        from .memory_monitor import MemoryMonitor

        mon = None
        while not self._stopped.is_set():
            ms = config.memory_monitor_refresh_ms
            if ms <= 0:
                self._stopped.wait(1.0)
                continue
            self._stopped.wait(ms / 1000.0)
            if mon is None or mon.threshold != config.memory_usage_threshold:
                mon = MemoryMonitor(config.memory_usage_threshold)
            try:
                res = self.handler.oom_tick(mon)
            except Exception:  # noqa: BLE001 — monitor must keep running
                continue
            if res is not None:
                with self._causes_lock:
                    self._pending_causes[res[0]] = res[1]

    @property
    def address(self) -> Tuple[str, int]:
        return self.server.address

    def notify_preemption(self, event) -> None:
        """Report this host's preemption (maintenance event / SIGTERM)
        to the conductor; it drains the host and broadcasts the
        checkpoint-now signal to affected gangs."""
        try:
            self._conductor.call("report_preemption", self.node_id, None,
                                 event.grace_s, event.reason, timeout=5.0)
        except Exception:  # noqa: BLE001 — conductor mid-restart: the
            pass           # next heartbeat re-establishes contact

    def _heartbeat_loop(self) -> None:
        from .config import config

        grace = config.node_orphan_grace
        last_ok = time.monotonic()
        pending_dead: List[str] = []
        while not self._stopped.wait(_heartbeat_period()):
            # chaos harness: scripted heartbeat delay (the "slow host"
            # failure mode — exercises the conductor's node timeout)
            from ray_tpu.resilience.chaos import heartbeat_delay_s

            delay = heartbeat_delay_s()
            if delay > 0 and self._stopped.wait(delay):
                break
            with self._causes_lock:
                causes = dict(self._pending_causes)
            pending_dead.extend(self.handler.reap_dead())
            try:
                known = self._conductor.call("node_heartbeat", self.node_id,
                                             pending_dead, causes,
                                             timeout=5.0)
                if not known:
                    # conductor restarted and lost us: re-register (keep
                    # the causes/dead lists for the next heartbeat)
                    self._conductor.call("register_node", self.node_id,
                                         self.resources, self.server.address,
                                         timeout=5.0)
                else:
                    pending_dead.clear()
                    with self._causes_lock:
                        for wid in causes:
                            self._pending_causes.pop(wid, None)
                last_ok = time.monotonic()
            except Exception:
                # tolerate a brief outage (conductor restart); a sustained
                # one means the cluster is gone -> shut this host down
                if time.monotonic() - last_ok > grace:
                    self.stop()
                    os._exit(0)

    def stop(self) -> None:
        self._stopped.set()
        if getattr(self, "_preemption_watcher", None) is not None:
            self._preemption_watcher.stop()
        self.handler._shutdown_workers()
        try:
            # force: this host is leaving whether or not leases are live;
            # the conductor frees them and restarts actors elsewhere
            self._conductor.call("deregister_node", self.node_id, True,
                                 timeout=2.0)
        except Exception:
            pass
        self.server.stop()
        self._conductor.close()


def main(argv: Optional[List[str]] = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(
        description="join a ray_tpu cluster as a worker host")
    ap.add_argument("--address", required=True, help="head host:port")
    ap.add_argument("--num-cpus", type=float,
                    default=float(os.cpu_count() or 1))
    ap.add_argument("--resources", default=None,
                    help='extra resources as JSON, e.g. \'{"TPU": 4}\'')
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--node-id", default=None,
                    help="pre-assigned node id (autoscaler providers "
                         "correlate launched nodes this way)")
    args = ap.parse_args(argv)
    host, port = args.address.rsplit(":", 1)
    resources = {"CPU": args.num_cpus}
    if args.resources:
        import json

        resources.update(json.loads(args.resources))
    agent = NodeAgent((host, int(port)), resources,
                      node_id=args.node_id).start()
    # daemon main only (a library must not hijack signals): SIGTERM —
    # how platforms reclaim a VM — becomes a preemption broadcast so
    # gangs on this host checkpoint before the processes die
    from ray_tpu.resilience.preemption import install_sigterm_notifier

    install_sigterm_notifier(agent.notify_preemption)
    print(f"node agent {agent.node_id[:12]} on {agent.address} "
          f"joined {args.address}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        agent.stop()


if __name__ == "__main__":
    main()
