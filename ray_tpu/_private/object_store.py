"""Ownership-based object store.

TPU-native replacement for the reference's plasma store + memory store
(/root/reference/src/ray/object_manager/plasma/, src/ray/core_worker/
store_provider/). Design differences, deliberate (SURVEY.md §7):

- No store daemon. The process that *creates* a value holds it (ownership, cf.
  reference reference_count.h:61); peers fetch from the holder via RPC. Large
  host objects are written to POSIX shared memory so same-host readers map them
  zero-copy — the role plasma plays — but the segment is owned by the creating
  worker, not a daemon. Device arrays never pass through here: they live in HBM
  and move via ICI/DCN collectives inside jitted programs (ray_tpu.parallel).
- Values above SHM_THRESHOLD go to shm (one segment per object, buffers
  8-byte aligned); below, they stay inline in the holder's heap and ride the
  RPC reply on fetch.
- Eviction: holder-side LRU cap (RAY_TPU_OBJECT_STORE_CAP bytes); evicted or
  lost objects can be reconstructed from lineage by the owner's TaskManager.

An optional C++ store (ray_tpu/_native/shm_store.cc) provides the same segment
layout with a slab allocator; object_store transparently uses it when built.
"""
from __future__ import annotations

import os
import struct
import tempfile
import threading
import time
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Any, Dict, List, Optional, Tuple

from . import serialization
from .ids import ObjectID

def cleanup_leaked_segments() -> int:
    """Unlink /dev/shm/rtpu_a_<pid>_* arena segments whose owning process
    is dead. SIGKILL'ed workers cannot unlink their own segments; left to
    accumulate they hold tmpfs RAM and measurably degrade the shm object
    plane (observed 20-30x on 100MB fetches at ~4GB of leakage). Called
    from cluster stop/start; returns the number removed."""
    import glob
    import re

    removed = 0
    for path in glob.glob("/dev/shm/rtpu_a_*"):
        m = re.match(r"rtpu_a_(\d+)_", os.path.basename(path))
        if not m:
            continue
        try:
            os.kill(int(m.group(1)), 0)
        except ProcessLookupError:
            try:
                os.unlink(path)
                removed += 1
            except OSError:
                pass
        except (PermissionError, OSError):
            pass  # alive under another uid / odd pid — not ours to touch
    return removed


def shm_threshold() -> int:
    """Bytes above which host objects go to shared memory — resolved via
    the flag table at use time (ray_config_def.h analog)."""
    from .config import config

    return config.shm_threshold


_ALIGN = 8


class ObjectRef:
    """Handle to a (possibly not-yet-computed) remote value.

    `locator` is the RPC address of the process that holds (or will hold) the
    value; `owner` is the address of the submitting process, which keeps the
    task lineage for reconstruction.
    """

    __slots__ = ("id", "locator", "owner", "__weakref__")

    def __init__(self, id: ObjectID | str | None = None,
                 locator: Optional[Tuple[str, int]] = None,
                 owner: Optional[Tuple[str, int]] = None):
        if isinstance(id, ObjectID):
            self.id = id.hex()
        else:
            self.id = id if id is not None else ObjectID().hex()
        self.locator = tuple(locator) if locator else None
        self.owner = tuple(owner) if owner else None
        # distributed refcounting (reference reference_count.h:61): every
        # handle instance is counted; the last drop releases/deregisters
        from . import refcount

        refcount.tracker.track(self.id, self.owner)

    def __del__(self):
        try:
            from . import refcount

            refcount.tracker.untrack(self.id)
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass

    def hex(self) -> str:
        return self.id

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other.id == self.id

    def __repr__(self):
        return f"ObjectRef({self.id[:12]}…)"

    def __reduce__(self):
        return (ObjectRef, (self.id, self.locator, self.owner))

    # await support (used by serve/data async paths)
    def __await__(self):
        from . import worker as _w

        value = yield from _w.global_worker.get_async(self).__await__()
        return value

    def future(self):
        from . import worker as _w

        return _w.global_worker.get_future(self)


@dataclass
class _Entry:
    meta: Optional[bytes] = None
    buffers: Optional[List[memoryview]] = None
    shm_name: Optional[str] = None
    layout: Optional[List[Tuple[int, int]]] = None  # (offset, size) per buffer
    shm: Optional[shared_memory.SharedMemory] = None
    arena_offset: Optional[int] = None  # owner-side: block to free on delete
    nbytes: int = 0
    error: Optional[BaseException] = None
    ready: bool = False
    last_access: float = field(default_factory=time.monotonic)
    pinned: int = 0
    # on-disk copy written by eviction-spill; data is restored (or range-
    # read) from here on next access (reference local_object_manager.h:53)
    spill_path: Optional[str] = None
    # True for entries whose bytes THIS process authored (put_value):
    # possibly the only copy in the cluster (the owner's locator may point
    # here). False for fetched caches, which are refetchable.
    primary: bool = False

    @property
    def in_memory(self) -> bool:
        return (self.buffers is not None or self.shm_name is not None
                or self.error is not None)


class LocalObjectStore:
    """Per-process store: holds objects this process created, caches fetched
    ones, and provides blocking get with readiness signaling."""

    def __init__(self, cap: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self._entries: Dict[str, _Entry] = {}
        self._cv = threading.Condition()
        self._attached: Dict[str, Any] = {}  # SharedMemory or attached Arena
        self._bytes = 0
        from .config import config

        self._cap = int(cap) if cap is not None else config.object_store_cap
        # Eviction SPILLS owned objects here instead of dropping them, so
        # put() beyond the memory cap stays correct (reference
        # local_object_manager.h:53 spill + restore)
        self._spill_dir = spill_dir or os.path.join(
            config.spill_dir or os.path.join(tempfile.gettempdir(),
                                             "ray_tpu_spill"),
            str(os.getpid()))
        # objects for which only a placeholder exists (awaiting task result)
        self._deserialized_cache: Dict[str, Any] = {}
        # Native C++ slab arena (shm_store.cc): one mapping for ALL of this
        # process's large objects — peers attach once and read at offsets
        # instead of one shm_open+mmap per object. None → per-object
        # SharedMemory fallback.
        self._arena = None
        if config.native_store:
            try:
                from ray_tpu._native import Arena

                self._arena = Arena.create(
                    f"rtpu_a_{os.getpid()}_{ObjectID().hex()[:8]}",
                    config.arena_size)
            except Exception:  # noqa: BLE001 — build/env issue: fall back
                self._arena = None
        # Freed arena blocks rest here ~2s before reuse so a peer mid-copy
        # of an exported object never reads recycled bytes (the reference
        # uses plasma pins; deferred reuse is the ownership-model analog).
        self._arena_quarantine: List[Tuple[float, int]] = []

    # ---------- write paths ----------

    def put_value(self, object_id: str, value: Any) -> int:
        """Serialize and store; returns total bytes."""
        meta, buffers = serialization.serialize(value)
        total = sum(b.nbytes for b in buffers)
        e = _Entry(meta=meta, nbytes=len(meta) + total, primary=True)
        if total >= shm_threshold():
            size = 0
            layout = []
            for b in buffers:
                off = (size + _ALIGN - 1) // _ALIGN * _ALIGN
                layout.append((off, b.nbytes))
                size = off + b.nbytes
            base = self._arena.alloc(max(size, 1)) if self._arena else 0
            if base:
                mem = self._arena.view(base, size)
                e.arena_offset = base
                e.shm_name = f"arena:{self._arena.name}"
                e.layout = [(base + off, n) for off, n in layout]
            else:  # no native store, or arena full: per-object segment
                shm = shared_memory.SharedMemory(create=True,
                                                 size=max(size, 1))
                mem = shm.buf
                e.shm, e.shm_name, e.layout = shm, shm.name, layout
            for (off, n), b in zip(layout, buffers):
                mem[off:off + n] = \
                    b.cast("B")[:] if b.format != "B" else b[:]
        else:
            e.buffers = [memoryview(bytes(b)) for b in buffers]
        e.ready = True
        with self._cv:
            self._entries[object_id] = e
            self._bytes += e.nbytes
            self._deserialized_cache[object_id] = value
            self._cv.notify_all()
        self._maybe_evict()
        return e.nbytes

    def put_serialized(self, object_id: str, meta: bytes,
                       buffers: List[memoryview], copy: bool = True) -> None:
        """copy=False adopts the buffers as-is (chunked-fetch assembly
        already owns a private bytearray — don't double the peak)."""
        e = _Entry(meta=meta,
                   buffers=[memoryview(bytes(b)) for b in buffers] if copy
                   else [memoryview(b) for b in buffers],
                   nbytes=len(meta) + sum(b.nbytes for b in buffers), ready=True)
        with self._cv:
            self._entries[object_id] = e
            self._bytes += e.nbytes
            self._cv.notify_all()
        self._maybe_evict()

    def put_shm_reference(self, object_id: str, meta: bytes, shm_name: str,
                          layout: List[Tuple[int, int]]) -> None:
        """Record a fetched same-host shm object (zero-copy read path)."""
        e = _Entry(meta=meta, shm_name=shm_name, layout=layout,
                   nbytes=len(meta), ready=True)
        with self._cv:
            self._entries[object_id] = e
            self._bytes += e.nbytes
            self._cv.notify_all()

    def put_error(self, object_id: str, error: BaseException) -> None:
        e = _Entry(error=error, ready=True)
        with self._cv:
            self._entries[object_id] = e
            self._cv.notify_all()

    def invalidate(self, object_id: str) -> None:
        """Drop a (possibly pending) entry so waiters see it as missing."""
        with self._cv:
            e = self._entries.pop(object_id, None)
            self._deserialized_cache.pop(object_id, None)
            if e is not None:
                self._bytes -= e.nbytes
                self._free_entry(e)
            self._cv.notify_all()

    # ---------- read paths ----------

    def contains(self, object_id: str) -> bool:
        with self._cv:
            e = self._entries.get(object_id)
            return e is not None and e.ready

    def size_of(self, object_id: str) -> int:
        """Stored size in bytes, 0 if absent/not ready (locality hints)."""
        with self._cv:
            e = self._entries.get(object_id)
            return e.nbytes if e is not None and e.ready else 0

    def notify_waiters(self) -> None:
        """Wake wait_ready()/Worker._wait_result waiters so they re-check
        out-of-store readiness signals (e.g. a large result recorded as a
        remote locator — no store entry is ever created for those)."""
        with self._cv:
            self._cv.notify_all()

    def wait_change(self, timeout: float) -> None:
        """Bounded wait for ANY readiness change (local puts, errors, and
        remote object_available pushes routed through notify_waiters).
        A wake between the caller's check and this wait is missed — the
        bounded timeout makes that a latency blip, never a hang."""
        with self._cv:
            self._cv.wait(timeout)

    def wait_ready_once(self, object_id: str, timeout: float) -> bool:
        """One bounded cv wait: True iff an entry for `object_id` is ready.
        Returns early (False) on any notify_waiters() wake so callers can
        re-check out-of-store readiness (locators, vanished submitters)
        without this module knowing about owner-side state."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is not None and e.ready:
                return True
            self._cv.wait(timeout)
            e = self._entries.get(object_id)
            return e is not None and e.ready

    def wait_ready(self, object_id: str, timeout: Optional[float]) -> bool:
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while True:
                e = self._entries.get(object_id)
                if e is not None and e.ready:
                    return True
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining if remaining is None or remaining < 0.2 else 0.2)

    def get_local(self, object_id: str) -> Any:
        """Deserialize a ready local entry (raises stored errors)."""
        with self._cv:
            if object_id in self._deserialized_cache:
                return self._deserialized_cache[object_id]
            e = self._entries.get(object_id)
            if e is None or not e.ready:
                raise KeyError(object_id)
            e.last_access = time.monotonic()
            if e.error is not None:
                raise e.error
            self._ensure_resident_locked(e)
            e.pinned += 1  # a concurrent eviction must not spill mid-read
        try:
            if e.shm_name is not None:
                if e.shm_name.startswith("arena:"):
                    # Arena blocks are RECYCLED after free (unlike per-object
                    # segments, whose pages survive unlink), so any deserialize
                    # that could outlive the entry copies out of the mapping —
                    # the ownership-model stand-in for plasma pins. In practice
                    # this path is cold: owner reads of own puts are served by
                    # _deserialized_cache above.
                    shm = (self._arena if e.arena_offset is not None
                           else self._attach(e.shm_name))
                    bufs = [memoryview(bytes(shm.buf[off:off + n]))
                            for off, n in e.layout]
                else:
                    shm = e.shm or self._attach(e.shm_name)
                    bufs = [memoryview(shm.buf)[off:off + n]
                            for off, n in e.layout]
            else:
                bufs = e.buffers or []
            value = serialization.deserialize(e.meta, bufs)
        finally:
            with self._cv:
                e.pinned -= 1
        with self._cv:
            self._deserialized_cache[object_id] = value
        self._maybe_evict()  # a restore may have pushed us over the cap
        return value

    def export(self, object_id: str) -> Tuple[bytes, Optional[str],
                                              Optional[List[Tuple[int, int]]],
                                              Optional[List[bytes]]]:
        """For serving a fetch RPC: (meta, shm_name, layout, inline_buffers)."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None or not e.ready:
                raise KeyError(object_id)
            if e.error is not None:
                raise e.error
            e.last_access = time.monotonic()
            self._ensure_resident_locked(e)
            if e.shm_name is not None:
                return e.meta, e.shm_name, e.layout, None
            return e.meta, None, None, [bytes(b) for b in (e.buffers or [])]

    # ---------- lifetime ----------

    def pin(self, object_id: str) -> None:
        with self._cv:
            e = self._entries.get(object_id)
            if e is not None:
                e.pinned += 1

    def unpin(self, object_id: str) -> None:
        with self._cv:
            e = self._entries.get(object_id)
            if e is not None and e.pinned > 0:
                e.pinned -= 1

    def delete(self, object_id: str) -> None:
        with self._cv:
            e = self._entries.pop(object_id, None)
            self._deserialized_cache.pop(object_id, None)
        if e is not None:
            with self._cv:
                self._bytes -= e.nbytes
            self._free_entry(e)

    def delete_cached(self, object_id: str) -> None:
        """Delete only if the entry is a fetched CACHE copy. A primary
        entry (bytes authored here — possibly the cluster's only copy,
        pointed at by the owner's locator) survives; the owner's
        free_objects is the authoritative release for those."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None or e.primary:
                return
            self._entries.pop(object_id, None)
            self._deserialized_cache.pop(object_id, None)
            self._bytes -= e.nbytes
        self._free_entry(e)

    _QUARANTINE_S = 2.0

    def _drain_quarantine(self, everything: bool = False) -> None:
        now = time.monotonic()
        with self._cv:
            if everything:
                ready = [o for _, o in self._arena_quarantine]
                self._arena_quarantine = []
            else:
                ready = [o for t, o in self._arena_quarantine if t <= now]
                self._arena_quarantine = [
                    (t, o) for t, o in self._arena_quarantine if t > now]
        if self._arena is not None:
            for off in ready:
                self._arena.free(off)

    def _free_entry(self, e: _Entry) -> None:
        if e.arena_offset is not None and self._arena is not None:
            with self._cv:
                self._arena_quarantine.append(
                    (time.monotonic() + self._QUARANTINE_S,
                     e.arena_offset))
            e.arena_offset = None
            self._drain_quarantine()
        if e.shm is not None:
            # unlink BEFORE close: close() raises BufferError when a
            # zero-copy deserialized array the user still holds references
            # the mapping — the name must be released regardless, and the
            # error must never abort the caller (eviction / delete paths);
            # the pages live until the last mapping drops.
            try:
                e.shm.unlink()
            except (FileNotFoundError, OSError):
                pass
            try:
                e.shm.close()
            except (OSError, BufferError):
                pass
        if e.spill_path is not None:
            try:
                os.unlink(e.spill_path)
            except OSError:
                pass
            e.spill_path = None

    def _attach(self, name: str):
        with self._cv:
            shm = self._attached.get(name)
            if shm is not None:
                return shm
        if name.startswith("arena:"):
            from ray_tpu._native import Arena

            shm = Arena.attach(name[len("arena:"):])
            if shm is None:
                raise KeyError(f"arena {name} is gone")
        else:
            shm = shared_memory.SharedMemory(name=name)
        with self._cv:
            self._attached[name] = shm
        return shm

    # ---------- spill / restore (reference local_object_manager.h:53) ----

    _SPILL_HDR = struct.Struct(">I")     # len(meta)
    _SPILL_CNT = struct.Struct(">I")     # n buffers
    _SPILL_SZ = struct.Struct(">Q")      # per-buffer size

    def _gather_buffers_locked(self, e: _Entry) -> Optional[List[memoryview]]:
        """Current in-memory payload views, or None if not resident."""
        if e.buffers is not None:
            return e.buffers
        if e.shm_name is not None and e.layout is not None:
            if e.shm_name.startswith("arena:"):
                shm = (self._arena if e.arena_offset is not None
                       else self._attached.get(e.shm_name))
                if shm is None:
                    return None
            else:
                shm = e.shm or self._attached.get(e.shm_name)
                if shm is None:
                    return None
            return [memoryview(shm.buf)[off:off + n] for off, n in e.layout]
        return None

    def _spill_entry_locked(self, oid: str, e: _Entry) -> bool:
        """Write payload to disk, then drop the memory copy. Must hold
        lock (eviction is the cold path; the write is tolerable here).

        Only entries whose bytes WE own are spillable. A zero-copy
        reference into another process's arena (put_shm_reference) may
        already point at recycled memory by the time we evict — spilling
        it would persist garbage as the object's value. Those are dropped
        and refetched instead."""
        owned = (e.buffers is not None or e.shm is not None
                 or e.arena_offset is not None)
        if not owned:
            return False
        bufs = self._gather_buffers_locked(e)
        if bufs is None:
            return False
        if e.spill_path is None or not os.path.exists(e.spill_path):
            os.makedirs(self._spill_dir, exist_ok=True)
            path = os.path.join(self._spill_dir, oid)
            tmp = path + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    f.write(self._SPILL_HDR.pack(len(e.meta or b"")))
                    f.write(e.meta or b"")
                    f.write(self._SPILL_CNT.pack(len(bufs)))
                    for b in bufs:
                        f.write(self._SPILL_SZ.pack(b.nbytes))
                    for b in bufs:
                        f.write(b.cast("B") if b.format != "B" else b)
                os.replace(tmp, path)
            except OSError:
                return False
            e.spill_path = path
        # free the memory copy (entry stays, ready, restorable)
        self._deserialized_cache.pop(oid, None)
        self._bytes -= e.nbytes
        if e.arena_offset is not None and self._arena is not None:
            self._arena_quarantine.append(
                (time.monotonic() + self._QUARANTINE_S, e.arena_offset))
            e.arena_offset = None
        if e.shm is not None:
            # unlink-then-close, tolerating BufferError — see _free_entry;
            # an exported buffer must never abort a spill under pressure
            try:
                e.shm.unlink()
            except (FileNotFoundError, OSError):
                pass
            try:
                e.shm.close()
            except (OSError, BufferError):
                pass
            e.shm = None
        e.buffers = None
        e.shm_name = None
        e.layout = None
        return True

    def _read_spill_header(self, f):
        """(meta, buffer_sizes, payload_file_offset) — cheap: no payload."""
        (meta_len,) = self._SPILL_HDR.unpack(f.read(self._SPILL_HDR.size))
        meta = f.read(meta_len)
        (n,) = self._SPILL_CNT.unpack(f.read(self._SPILL_CNT.size))
        sizes = [self._SPILL_SZ.unpack(f.read(self._SPILL_SZ.size))[0]
                 for _ in range(n)]
        return meta, sizes, f.tell()

    def _read_spill_file(self, path: str):
        with open(path, "rb") as f:
            meta, sizes, _ = self._read_spill_header(f)
            bufs = [memoryview(f.read(sz)) for sz in sizes]
        return meta, bufs

    def _read_spill_range(self, path: str, start: int, size: int) -> bytes:
        """Seek-and-read: a chunked fetch of a spilled multi-GB object
        must not load (or re-load) the whole file per chunk."""
        with open(path, "rb") as f:
            _, sizes, data_off = self._read_spill_header(f)
            total = sum(sizes)
            start = min(start, total)
            f.seek(data_off + start)
            return f.read(min(size, total - start))

    def _restore_locked(self, e: _Entry) -> None:
        """Load a spilled entry back into heap buffers. The spill file is
        kept: a later re-evict of an unmodified object is then free."""
        meta, bufs = self._read_spill_file(e.spill_path)
        e.meta = meta
        e.buffers = bufs
        e.layout = None
        self._bytes += e.nbytes

    def _ensure_resident_locked(self, e: _Entry) -> None:
        if not e.in_memory and e.spill_path is not None:
            self._restore_locked(e)

    # ---------- chunked streaming (reference pull_manager.cc 64MB) -------

    def stream_info(self, object_id: str):
        """(meta, total_payload_bytes, buffer_sizes) without forcing a
        spilled object back into memory — the remote-fetch header."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None or not e.ready:
                raise KeyError(object_id)
            if e.error is not None:
                raise e.error
            e.last_access = time.monotonic()
            if e.in_memory:
                bufs = self._gather_buffers_locked(e)
                if bufs is None:
                    raise KeyError(object_id)
                return e.meta, sum(b.nbytes for b in bufs), \
                    [b.nbytes for b in bufs]
            with open(e.spill_path, "rb") as f:
                meta, sizes, _ = self._read_spill_header(f)
            return meta, sum(sizes), sizes

    def read_range(self, object_id: str, start: int, size: int) -> bytes:
        """Bytes [start, start+size) of the object's payload stream (all
        buffers concatenated). Serves from memory or the spill file."""
        with self._cv:
            e = self._entries.get(object_id)
            if e is None or not e.ready:
                raise KeyError(object_id)
            if e.error is not None:
                raise e.error
            e.last_access = time.monotonic()
            bufs = self._gather_buffers_locked(e) if e.in_memory else None
            if bufs is None and e.spill_path is not None:
                return self._read_spill_range(e.spill_path, start, size)
            if bufs is None:
                raise KeyError(object_id)
            out = bytearray()
            pos = 0
            for b in bufs:
                if size <= 0:
                    break
                b = b.cast("B") if b.format != "B" else b
                if pos + b.nbytes > start:
                    lo = max(0, start - pos)
                    take = min(b.nbytes - lo, size)
                    out += b[lo:lo + take]
                    size -= take
                    start += take
                pos += b.nbytes
            return bytes(out)

    def _maybe_evict(self) -> None:
        self._drain_quarantine()
        with self._cv:
            if self._bytes <= self._cap:
                return
            entries = sorted(
                ((oid, e) for oid, e in self._entries.items()
                 if e.ready and e.pinned == 0 and e.error is None
                 and e.in_memory),
                key=lambda kv: kv[1].last_access)
            for oid, e in entries:
                if self._bytes <= self._cap * 0.8:
                    break
                if self._spill_entry_locked(oid, e):
                    continue
                # not ours to spill (zero-copy reference into another
                # process's memory): drop — it is refetchable
                self._entries.pop(oid, None)
                self._deserialized_cache.pop(oid, None)
                self._bytes -= e.nbytes
                self._free_entry(e)

    def stats(self) -> Dict[str, int]:
        with self._cv:
            spilled = [e for e in self._entries.values()
                       if not e.in_memory and e.spill_path is not None]
            return {"num_objects": len(self._entries), "bytes": self._bytes,
                    "spilled_objects": len(spilled),
                    "spilled_bytes": sum(e.nbytes for e in spilled)}

    def shutdown(self) -> None:
        with self._cv:
            entries = list(self._entries.values())
            self._entries.clear()
            self._deserialized_cache.clear()
            attached = list(self._attached.values())
            self._attached.clear()
        for e in entries:
            self._free_entry(e)
        for shm in attached:
            try:
                shm.close()
            except OSError:
                pass
        if self._arena is not None:
            # unlink the name only — munmap here would SIGSEGV any zero-copy
            # array the user still holds; the mapping dies with the process.
            self._arena.unlink_only()
            self._arena = None
