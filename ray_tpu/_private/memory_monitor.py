"""Node memory monitor / OOM protection.

Reference: src/ray/common/memory_monitor.h:52 (cgroup-aware node usage
polling) + src/ray/raylet/worker_killing_policy.cc (victim choice). A
runaway task must not hand the host to the kernel OOM killer — which
kills arbitrary processes, possibly the conductor, with zero diagnosis.
Instead the conductor (head node) and each node agent poll node usage
every refresh interval; above the threshold they SIGKILL the worker
using the most memory — task workers before actors before idle workers,
matching the reference's "prefer retriable work" policy — and record
the death as OOM so the submitter raises OutOfMemoryError (with usage
numbers) rather than a bare WorkerCrashedError.
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence, Tuple

# worker states in kill-preference order: running tasks are retriable,
# actors lose state, idle workers free the least
_KILL_ORDER = {"BUSY": 0, "ACTOR": 1, "IDLE": 2}


def _read_first_int(path: str) -> Optional[int]:
    try:
        with open(path) as f:
            txt = f.read().strip()
        if txt == "max":
            return None
        return int(txt.split()[0])
    except (OSError, ValueError):
        return None


def cgroup_limit_and_usage() -> Tuple[Optional[int], Optional[int]]:
    """(limit, used) from cgroup v2 then v1, None when unlimited/absent
    (reference memory_monitor.cc GetCGroupMemoryLimit/UsedBytes)."""
    limit = _read_first_int("/sys/fs/cgroup/memory.max")
    used = _read_first_int("/sys/fs/cgroup/memory.current")
    if limit is None or used is None:
        limit = limit or _read_first_int(
            "/sys/fs/cgroup/memory/memory.limit_in_bytes")
        used = used or _read_first_int(
            "/sys/fs/cgroup/memory/memory.usage_in_bytes")
    # a v1 "unlimited" reads as a huge number; treat >= 2^60 as no limit
    if limit is not None and limit >= 1 << 60:
        limit = None
    return limit, used


def proc_meminfo() -> Tuple[int, int]:
    """(total, available) bytes from /proc/meminfo."""
    total = avail = 0
    with open("/proc/meminfo") as f:
        for line in f:
            if line.startswith("MemTotal:"):
                total = int(line.split()[1]) * 1024
            elif line.startswith("MemAvailable:"):
                avail = int(line.split()[1]) * 1024
            if total and avail:
                break
    return total, avail


def node_usage() -> Tuple[int, int]:
    """(used, total) for this node: the tighter of the cgroup limit and
    the host's physical memory."""
    total, avail = proc_meminfo()
    used = total - avail
    climit, cused = cgroup_limit_and_usage()
    if climit is not None and cused is not None and climit < total:
        return cused, climit
    return used, total


def pid_rss(pid: int) -> int:
    """Resident set size of `pid` in bytes (0 if gone)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class MemoryMonitor:
    """Threshold check + victim selection; pure logic with injectable
    readers so policy is unit-testable without real memory pressure."""

    def __init__(self, threshold: float,
                 usage_fn: Callable[[], Tuple[int, int]] = node_usage,
                 rss_fn: Callable[[int], int] = pid_rss):
        self.threshold = threshold
        self._usage_fn = usage_fn
        self._rss_fn = rss_fn

    def over_threshold(self) -> Optional[Tuple[int, int]]:
        """(used, total) when the node is above the kill threshold."""
        if self.threshold <= 0:
            return None
        used, total = self._usage_fn()
        if total > 0 and used / total > self.threshold:
            return used, total
        return None

    def kill_greediest(self, workers: Sequence[Tuple[str, int, str]],
                       node_label: str = ""
                       ) -> Optional[Tuple[str, str]]:
        """Full monitor tick shared by conductor and node agent: if the
        node is over threshold, SIGKILL the chosen victim and return
        (worker_id, cause). No cause is reported when the kill failed —
        a process that exited on its own in the pick→kill window must
        not be mislabeled as OOM-killed."""
        over = self.over_threshold()
        if over is None:
            return None
        used, total = over
        victim = self.pick_victim(workers)
        if victim is None:
            return None
        worker_id, pid, rss = victim
        try:
            os.kill(pid, 9)
        except OSError:
            return None
        label = f"node {node_label} " if node_label else "node "
        return worker_id, (
            f"oom: {label}memory {used}/{total} bytes "
            f"({used / max(1, total):.0%}) over threshold "
            f"{self.threshold:.0%}; killed greediest worker "
            f"(rss {rss} bytes)")

    def pick_victim(self, workers: Sequence[Tuple[str, int, str]]
                    ) -> Optional[Tuple[str, int, int]]:
        """workers: (worker_id, pid, state). Returns (worker_id, pid,
        rss) of the victim: the highest-RSS worker in the most
        killable state class present."""
        best: Optional[Tuple[str, int, int]] = None
        best_key: Optional[Tuple[int, int]] = None
        for worker_id, pid, state in workers:
            order = _KILL_ORDER.get(state)
            if order is None or pid is None:
                continue
            rss = self._rss_fn(pid)
            if rss <= 0:
                continue
            key = (order, -rss)
            if best_key is None or key < best_key:
                best_key = key
                best = (worker_id, pid, rss)
        return best
