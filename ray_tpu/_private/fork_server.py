"""Pre-warmed worker fork server.

Worker spawn latency is interpreter + import cost (~200ms on a small
host) paid on every pool scale-up and every actor creation — the
dominant term in actor churn. This template process pre-imports the
worker stack ONCE, then serves spawn requests by forking: the child becomes the worker
(reaped by this template's SIGCHLD handler the moment it exits) and
starts in ~10ms with all modules hot.

Reference anchor: the raylet worker pool amortizes the same cost by
prestarting idle workers (src/ray/raylet/worker_pool.h:343 PopWorker /
prestart); CPython's multiprocessing "forkserver" start method is the
standard shape of this solution. We need the explicit version because
workers are re-parented across processes (conductor restarts must not
kill the fleet) and each spawn needs its own env + log wiring.

Fork safety: this process must stay single-threaded — it imports the
worker modules (imports start no threads; threads appear only when a
Worker object is constructed in the forked child) and serves a unix
socket sequentially. Liveness is tied to the parent conductor via a
ppid poll in the accept loop, not PDEATHSIG (which has per-thread
semantics on linux and the conductor forks from pool threads).
"""
from __future__ import annotations

import os
import pickle
import socket
import struct
import sys


def _read_exact(conn: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = conn.recv(n - len(buf))
        if not chunk:
            raise EOFError("fork-server request truncated")
        buf += chunk
    return buf


def _spawn_from_request(srv: socket.socket, conn: socket.socket,
                        req: dict) -> None:
    # single fork: the worker stays a direct child of the template,
    # which reaps it via its SIGCHLD handler the moment it exits. (The
    # earlier double-fork orphaned workers to pid 1, whose reaper on
    # this platform lags ~1.5s — during that zombie window the
    # conductor's os.kill(pid, 0) liveness probe still "saw" the dead
    # worker and cluster teardown stalled on it.)
    import signal

    pid = os.fork()
    if pid == 0:
        # child: become the worker
        conn_fd = conn.fileno()
        srv.close()
        os.setsid()
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        log_fd = os.open(req["log_path"],
                         os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(log_fd, 1)
        os.dup2(log_fd, 2)
        os.close(log_fd)
        os.close(conn_fd)
        os.environ.clear()
        os.environ.update(req["env"])
        for p in req.get("sys_path_extra", ()):
            if p not in sys.path:
                sys.path.insert(0, p)
        from ray_tpu._private import worker_main

        try:
            worker_main.main()
        finally:
            os._exit(0)
    conn.sendall(struct.pack("<i", pid))


def serve(sock_path: str) -> None:
    # warm the import cache before any fork — this is the entire point
    import ray_tpu._private.worker  # noqa: F401
    import ray_tpu._private.worker_main  # noqa: F401
    import ray_tpu._private.serialization  # noqa: F401
    import signal

    def _reap(_sig, _frm):
        try:
            while os.waitpid(-1, os.WNOHANG)[0]:
                pass
        except ChildProcessError:
            pass

    # prompt reaping: dead workers must vanish from the pid table
    # immediately so the conductor's signal-0 liveness probes see them
    # gone (PEP 475 re-runs accept() after the handler fires)
    signal.signal(signal.SIGCHLD, _reap)

    parent = os.getppid()
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    srv.bind(sock_path)
    srv.listen(16)
    srv.settimeout(2.0)
    sys.stdout.write("READY\n")
    sys.stdout.flush()
    while True:
        try:
            conn, _ = srv.accept()
        except socket.timeout:
            if os.getppid() != parent:  # conductor gone: die with it
                break
            continue
        except OSError:
            break
        try:
            (size,) = struct.unpack("<I", _read_exact(conn, 4))
            req = pickle.loads(_read_exact(conn, size))
            _spawn_from_request(srv, conn, req)
        except (EOFError, OSError, pickle.UnpicklingError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
    try:
        os.unlink(sock_path)
    except OSError:
        pass


if __name__ == "__main__":
    serve(sys.argv[1])
