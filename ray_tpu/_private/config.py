"""Runtime flag table — the one place to see and override every knob.

Analog of the reference's `src/ray/common/ray_config_def.h` (219
RAY_CONFIG entries materialized into a singleton RayConfig) and the
`_system_config` dict accepted by ray.init. Here each flag is declared
once with its type, default, and doc; the value resolves as

    explicit _system_config override  >  RAY_TPU_<NAME> env var  >  default

Overrides are exported back into the environment so worker/agent child
processes (and the scattered lazy `os.environ` reads across the
codebase) all see one consistent value.
"""
from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

_ENV_PREFIX = "RAY_TPU_"


@dataclass(frozen=True)
class Flag:
    name: str            # lower_snake; env var is RAY_TPU_<upper>
    type: type
    default: Any
    doc: str

    @property
    def env_var(self) -> str:
        return _ENV_PREFIX + self.name.upper()


_FLAGS: List[Flag] = [
    # --- control plane -------------------------------------------------
    Flag("worker_start_timeout", float, 60.0,
         "seconds a lease waits for a worker process to start"),
    Flag("node_timeout", float, 10.0,
         "seconds without a heartbeat before an agent node is dead"),
    Flag("node_heartbeat", float, 1.0,
         "node agent heartbeat period (seconds)"),
    Flag("worker_orphan_grace", float, 30.0,
         "seconds a worker outlives a dead conductor before exiting"),
    Flag("node_orphan_grace", float, 30.0,
         "seconds a node agent outlives a dead conductor before exiting"),
    Flag("restore_grace", float, 20.0,
         "seconds a snapshot-restored worker record is presumed alive "
         "awaiting its re-register"),
    Flag("lease_idle_ttl", float, 0.1,
         "seconds a submitter keeps an idle worker lease for reuse "
         "before returning it to the conductor (reference: direct task "
         "submitter worker-lease caching)"),
    # --- object plane --------------------------------------------------
    Flag("object_store_cap", int, 2 * 1024**3,
         "per-process object store memory cap in bytes; eviction spills "
         "past it"),
    Flag("shm_threshold", int, 100 * 1024,
         "bytes above which host objects go to shared memory"),
    Flag("arena_size", int, 2 * 1024**3,
         "native shm arena size in bytes"),
    Flag("native_store", int, 1,
         "1 = use the C++ slab arena (shm_store.cc); 0 = per-object "
         "SharedMemory segments"),
    Flag("fetch_chunk", int, 64 * 1024 * 1024,
         "chunk size for cross-host object pulls"),
    Flag("spill_dir", str, "",
         "directory for eviction spill files (default: tmp)"),
    Flag("data_memory_budget", int, 512 * 1024 * 1024,
         "per-operator in-flight byte budget for Dataset execution "
         "(0 disables; reference data ResourceManager memory budgets)"),
    Flag("data_shm_high_water", float, 0.85,
         "host /dev/shm usage fraction above which Dataset operators "
         "stall task admission (reference object-store backpressure)"),
    Flag("force_remote_fetch", int, 0,
         "testing: every process claims a distinct machine id, forcing "
         "the cross-host chunked fetch path"),
    # --- accelerators --------------------------------------------------
    Flag("chips", int, 0,
         "override detected TPU chip count (0 = autodetect)"),
    Flag("pallas_interpret", int, 0,
         "run Pallas kernels in interpret mode (CPU testing)"),
    # --- memory monitor ------------------------------------------------
    Flag("memory_usage_threshold", float, 0.95,
         "node memory fraction above which the monitor OOM-kills the "
         "greediest worker (<= 0 disables; reference memory_monitor.h)"),
    Flag("memory_monitor_refresh_ms", int, 250,
         "memory monitor poll period in milliseconds (0 disables)"),
    Flag("log_to_driver", int, 1,
         "1 = mirror worker stdout/stderr lines to the driver console "
         "via the worker_logs pubsub channel (reference log_monitor.py)"),
    # --- resilience ----------------------------------------------------
    Flag("preempt_grace_s", float, 30.0,
         "default preemption grace window (seconds) when the "
         "maintenance-event channel does not specify one"),
    Flag("maintenance_poll_s", float, 1.0,
         "poll period of the RAY_TPU_MAINTENANCE_EVENT file watcher"),
    Flag("quarantine_threshold", float, 3.0,
         "decayed failure score at which a host is quarantined out of "
         "lease grants and gang formation (ray_tpu.resilience)"),
    Flag("quarantine_halflife_s", float, 600.0,
         "half-life (seconds) of a host's failure score decay"),
    Flag("restart_backoff_base_s", float, 1.0,
         "base delay of the trainer's exponential restart backoff"),
    Flag("restart_backoff_max_s", float, 30.0,
         "cap on the trainer's restart backoff delay"),
    # --- weight fabric -------------------------------------------------
    Flag("weights_keep", int, 3,
         "committed weight versions the registry keeps per name; older "
         "manifests are dropped and their chunks reaped (ray_tpu.weights)"),
    Flag("weights_publish_ttl_s", float, 120.0,
         "age at which a partially-committed weight publish (a producer "
         "died mid-publish) is reaped from the registry"),
    # --- misc ----------------------------------------------------------
    Flag("node_ip", str, "",
         "address other hosts can reach this one on (else inferred from "
         "the route to the conductor)"),
    Flag("workflow_storage", str, "",
         "workflow checkpoint root (default: ~/.ray_tpu_workflows)"),
]

_BY_NAME: Dict[str, Flag] = {f.name: f for f in _FLAGS}


def _coerce(flag: Flag, raw: Any) -> Any:
    if isinstance(raw, str) and flag.type is not str:
        return flag.type(float(raw)) if flag.type is int else flag.type(raw)
    return flag.type(raw)


class RayTpuConfig:
    """Resolved view of every flag; `apply` installs overrides."""

    def get(self, name: str) -> Any:
        flag = _BY_NAME.get(name)
        if flag is None:
            raise KeyError(f"unknown config flag {name!r}; known: "
                           f"{sorted(_BY_NAME)}")
        raw = os.environ.get(flag.env_var)
        if raw is None or raw == "":
            return flag.default
        return _coerce(flag, raw)

    def __getattr__(self, name: str) -> Any:
        if name.startswith("_"):
            raise AttributeError(name)
        return self.get(name)

    def apply(self, overrides: Dict[str, Any]) -> Dict[str, Optional[str]]:
        """Install `_system_config` overrides: validated against the
        table and exported to the environment so child processes and
        lazy readers agree. Returns {env_var: previous value or None}
        for `restore` — a cluster's overrides must die with it, not
        poison the next cluster in this process."""
        prior: Dict[str, Optional[str]] = {}
        for name, value in overrides.items():
            flag = _BY_NAME.get(name)
            if flag is None:
                raise ValueError(
                    f"unknown _system_config flag {name!r}; known flags: "
                    f"{sorted(_BY_NAME)}")
            prior[flag.env_var] = os.environ.get(flag.env_var)
            os.environ[flag.env_var] = str(_coerce(flag, value))
        return prior

    @staticmethod
    def restore(prior: Dict[str, Optional[str]]) -> None:
        """Undo an `apply` using its returned token."""
        for var, old in prior.items():
            if old is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = old

    def describe(self) -> List[Dict[str, Any]]:
        """All flags with their current value and provenance — the
        `ray_tpu config` CLI listing."""
        out = []
        for f in _FLAGS:
            raw = os.environ.get(f.env_var)
            out.append({
                "name": f.name, "env_var": f.env_var,
                "type": f.type.__name__, "default": f.default,
                "value": self.get(f.name),
                "source": "env" if raw not in (None, "") else "default",
                "doc": f.doc,
            })
        return out


config = RayTpuConfig()
