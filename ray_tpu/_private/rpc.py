"""Lightweight RPC: length-prefixed pickle frames over TCP.

Fills the role of the reference's gRPC wrapper layer
(/root/reference/src/ray/rpc/grpc_server.h, client_call.h): async server calls
dispatched to handler methods, clients with persistent connections, concurrent
in-flight requests demultiplexed by request id, and error propagation. We use
framed cloudpickle instead of protobuf because the control-plane schema here is
Python-internal; the data plane (tensors) never rides this path — it moves via
shared memory on-node (see object_store.py) and via ICI/DCN collectives
on-device (see ray_tpu.parallel).

Wire format: 8-byte big-endian length, then a pickled tuple:
  request:  (req_id, method_name, args, kwargs)   req_id < 0 => one-way
  response: (req_id, ok_flag, payload)            payload = result | exc info
"""
from __future__ import annotations

import os
import socket
import struct
import sys
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional, Tuple

from . import serialization

_LEN = struct.Struct(">Q")


class RpcError(RuntimeError):
    pass


class ConnectionLost(RpcError):
    pass


class RemoteError(RpcError):
    """An exception raised inside the remote handler."""

    def __init__(self, exc: BaseException, tb: str):
        super().__init__(f"{type(exc).__name__}: {exc}\n--- remote traceback ---\n{tb}")
        self.cause = exc
        self.remote_traceback = tb


def _send_frame(sock: socket.socket, payload: bytes, lock: threading.Lock) -> None:
    with lock:
        sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionLost("socket closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, 8))
    return _recv_exact(sock, length)


class RpcServer:
    """Threaded RPC server dispatching frames to methods of a handler object.

    Handler methods are looked up by name; names starting with '_' are not
    callable remotely. Each request runs on a pool thread so slow handlers
    don't block the connection's read loop (needed for concurrent actor calls).
    """

    def __init__(self, handler: Any, host: str = "127.0.0.1", port: int = 0,
                 max_workers: int = 16, warn_slow: bool = False):
        self._handler = handler
        # per-method latency accounting (reference instrumented_io_context
        # .h: post/dispatch counts + queueing and execution times).
        # warn_slow is for CONTROL-PLANE servers (the conductor): worker
        # servers run user task code inline in push_task, where >1s is
        # normal, not dispatch lag. Handlers that block BY DESIGN
        # (lease_worker parks on a condition variable until capacity
        # frees) opt out via the handler's _slow_ok_methods set.
        # 5s default: create_actor legitimately takes ~2-3s (process
        # spawn + imports); the warning is for wedged handlers.
        self._warn_slow = warn_slow
        self._warn_handler_s = float(
            os.environ.get("RAY_TPU_RPC_WARN_MS", "5000")) / 1e3
        self._slow_ok = frozenset(getattr(handler, "_slow_ok_methods",
                                          ()))
        self._stats: Dict[str, list] = {}
        self._stats_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if port == 0:
            self._sock.bind((host, port))
        else:
            # explicit port = a daemon restarting at a known address; the
            # previous incarnation's sockets may linger in FIN_WAIT for a
            # moment after its stop() — retry briefly instead of failing
            deadline = time.monotonic() + 5.0
            while True:
                try:
                    self._sock.bind((host, port))
                    break
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.1)
        self._sock.listen(128)
        self.address: Tuple[str, int] = self._sock.getsockname()
        self._pool = ThreadPoolExecutor(max_workers=max_workers,
                                        thread_name_prefix="rpc-handler")
        self._stopped = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               name="rpc-accept", daemon=True)
        self._conns: set = set()
        self._conns_lock = threading.Lock()

    def start(self) -> "RpcServer":
        # Every runtime process (conductor, workers, drivers) hosts an
        # RpcServer, so this is the one shared hook for the interpreter
        # switch interval. The 5ms CPython default turns concurrent RPC
        # dispatch into a GIL convoy — with 16 in-flight control-plane
        # calls, each handler waits ~n_runnable x 5ms for the GIL and
        # pipelined task throughput collapses ~6x below serial. 1ms keeps
        # dispatch latency bounded without measurably taxing compute
        # threads (jax releases the GIL during device execution).
        if sys.getswitchinterval() > 0.001:
            sys.setswitchinterval(0.001)
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            for c in list(self._conns):
                try:
                    c.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    c.close()
                except OSError:
                    pass
        self._pool.shutdown(wait=False, cancel_futures=True)

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             name="rpc-conn", daemon=True).start()

    def _conn_loop(self, conn: socket.socket) -> None:
        send_lock = threading.Lock()
        # Methods listed here are invoked synchronously on this reader
        # thread with a reply callback as first argument, preserving frame
        # ARRIVAL order (needed for actor task ordering) and freeing pool
        # threads from blocking on long-running handlers.
        async_reply = getattr(self._handler, "_async_reply_methods",
                              frozenset())
        try:
            while not self._stopped.is_set():
                frame = _recv_frame(conn)
                req_id, method, args, kwargs = serialization.loads(frame)
                if method in async_reply and req_id >= 0:
                    self._dispatch_async_reply(conn, send_lock, req_id,
                                               method, args, kwargs)
                else:
                    self._pool.submit(self._dispatch, conn, send_lock,
                                      req_id, method, args, kwargs,
                                      time.perf_counter())
                # a reader blocked in the next _recv_frame must not pin
                # the previous request in its frame locals: task args can
                # hold large values and ObjectRefs whose refcount release
                # (and memory) would otherwise wait for the NEXT request
                del frame, args, kwargs
        except (ConnectionLost, OSError):
            pass
        except RuntimeError:
            # pool shut down mid-race with stop(); drop the request
            pass
        finally:
            with self._conns_lock:
                self._conns.discard(conn)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch_async_reply(self, conn, send_lock, req_id, method, args,
                              kwargs) -> None:
        """Run an enqueue-style handler inline; it replies later via cb."""

        def reply_cb(ok: bool, payload: Any) -> None:
            try:
                _send_frame(conn, serialization.dumps((req_id, ok, payload)),
                            send_lock)
            except (OSError, ConnectionLost):
                pass

        try:
            getattr(self._handler, method)(reply_cb, *args, **kwargs)
        except BaseException as e:  # noqa: BLE001 — must cross the wire
            reply_cb(False, (e, traceback.format_exc()))

    def _record(self, method: str, queue_s: float, exec_s: float) -> None:
        with self._stats_lock:
            s = self._stats.get(method)
            if s is None:
                s = self._stats[method] = [0, 0.0, 0.0, 0.0, 0.0]
            s[0] += 1
            s[1] += queue_s
            s[2] += exec_s
            s[3] = max(s[3], queue_s)
            s[4] = max(s[4], exec_s)
        if self._warn_slow and exec_s > self._warn_handler_s \
                and method not in self._slow_ok:
            print(f"[rpc] slow handler {method}: {exec_s * 1e3:.0f}ms "
                  f"(queued {queue_s * 1e3:.0f}ms)", file=sys.stderr)

    def handler_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-method dispatch stats: count, mean/max queue and handler
        time (ms) — the instrumented_io_context analog for this server's
        thread pool."""
        with self._stats_lock:
            return {m: {"count": s[0],
                        "mean_queue_ms": s[1] / s[0] * 1e3,
                        "mean_handler_ms": s[2] / s[0] * 1e3,
                        "max_queue_ms": s[3] * 1e3,
                        "max_handler_ms": s[4] * 1e3}
                    for m, s in self._stats.items() if s[0]}

    def _dispatch(self, conn, send_lock, req_id, method, args, kwargs,
                  enqueued_at: float = 0.0) -> None:
        t0 = time.perf_counter()
        try:
            if method.startswith("_"):
                raise AttributeError(f"method {method!r} is not remotely callable")
            fn = getattr(self._handler, method)
            result = fn(*args, **kwargs)
            ok = True
        except BaseException as e:  # noqa: BLE001 — must cross the wire
            result = (e, traceback.format_exc())
            ok = False
        self._record(method, t0 - enqueued_at if enqueued_at else 0.0,
                     time.perf_counter() - t0)
        if req_id < 0:  # one-way
            return
        try:
            _send_frame(conn, serialization.dumps((req_id, ok, result)), send_lock)
        except (OSError, ConnectionLost):
            pass
        except Exception:
            # result unpicklable: send the error instead
            try:
                err = (RpcError(f"unpicklable result from {method}"),
                       traceback.format_exc())
                _send_frame(conn, serialization.dumps((req_id, False, err)), send_lock)
            except (OSError, ConnectionLost):
                pass


class RpcClient:
    """Persistent connection with concurrent in-flight calls."""

    def __init__(self, address: Tuple[str, int], connect_timeout: float = 10.0,
                 connect_retries: int = 0, retry_interval: float = 0.3):
        self.address = tuple(address)
        attempt = 0
        while True:
            try:
                self._sock = socket.create_connection(
                    self.address, timeout=connect_timeout)
                break
            except (ConnectionRefusedError, OSError) as e:
                attempt += 1
                if attempt > connect_retries:
                    raise ConnectionLost(
                        f"cannot connect to {self.address}: {e}") from e
                time.sleep(retry_interval)
        self._sock.settimeout(None)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._send_lock = threading.Lock()
        self._pending: Dict[int, "_Pending"] = {}
        self._pending_lock = threading.Lock()
        self._next_id = 1
        self._closed = False
        self._reader = threading.Thread(target=self._read_loop,
                                        name="rpc-reader", daemon=True)
        self._reader.start()

    def close(self) -> None:
        self._closed = True
        try:
            # shutdown() (not just close()) reliably wakes a reader thread
            # blocked in recv() on another thread's socket.
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def _read_loop(self) -> None:
        try:
            while True:
                frame = _recv_frame(self._sock)
                req_id, ok, payload = serialization.loads(frame)
                with self._pending_lock:
                    p = self._pending.pop(req_id, None)
                if p is not None:
                    p.ok, p.payload = ok, payload
                    p.event.set()
                # idle reader must not pin the last reply (may be a large
                # task result) until the next one arrives
                del frame, payload, p
        except (ConnectionLost, OSError, EOFError):
            self._closed = True
            with self._pending_lock:
                pending, self._pending = self._pending, {}
            for p in pending.values():
                p.ok = False
                p.payload = (ConnectionLost(f"connection to {self.address} lost"), "")
                p.event.set()

    def start_call(self, method: str, *args, **kwargs) -> "_Pending":
        """Send the request; returns a pending to pass to finish_call.
        Splitting send from wait lets callers control frame ordering."""
        p = _Pending()
        with self._pending_lock:
            req_id = self._next_id
            self._next_id += 1
            self._pending[req_id] = p
            p.req_id = req_id
        frame = serialization.dumps((req_id, method, args, kwargs))
        try:
            _send_frame(self._sock, frame, self._send_lock)
        except (OSError, ConnectionLost) as e:
            with self._pending_lock:
                self._pending.pop(req_id, None)
            raise ConnectionLost(str(e)) from e
        return p

    def finish_call(self, p: "_Pending", method: str = "",
                    timeout: Optional[float] = None) -> Any:
        if not p.event.wait(timeout):
            with self._pending_lock:
                self._pending.pop(p.req_id, None)
            raise TimeoutError(f"rpc {method} to {self.address} timed out after {timeout}s")
        if p.ok:
            return p.payload
        exc, tb = p.payload
        if isinstance(exc, ConnectionLost):
            raise exc
        raise RemoteError(exc, tb) from exc

    def call(self, method: str, *args, timeout: Optional[float] = None, **kwargs) -> Any:
        return self.finish_call(self.start_call(method, *args, **kwargs),
                                method, timeout)

    def notify(self, method: str, *args, **kwargs) -> None:
        """Fire-and-forget."""
        frame = serialization.dumps((-1, method, args, kwargs))
        try:
            _send_frame(self._sock, frame, self._send_lock)
        except OSError as e:
            raise ConnectionLost(str(e)) from e


class _Pending:
    __slots__ = ("event", "ok", "payload", "req_id")

    def __init__(self):
        self.event = threading.Event()
        self.ok = False
        self.payload = None
        self.req_id = -1


class ClientPool:
    """Cache of RpcClients keyed by address — analog of the reference's
    core_worker_client_pool.h."""

    def __init__(self):
        self._clients: Dict[Tuple[str, int], RpcClient] = {}
        self._lock = threading.Lock()

    def get(self, address: Tuple[str, int]) -> RpcClient:
        address = tuple(address)
        with self._lock:
            c = self._clients.get(address)
            if c is not None and not c._closed:
                return c
        c = RpcClient(address)
        with self._lock:
            old = self._clients.get(address)
            if old is not None and not old._closed:
                c.close()
                return old
            self._clients[address] = c
            return c

    def invalidate(self, address: Tuple[str, int]) -> None:
        with self._lock:
            c = self._clients.pop(tuple(address), None)
        if c is not None:
            c.close()

    def close_all(self) -> None:
        with self._lock:
            for c in self._clients.values():
                c.close()
            self._clients.clear()


class ReconnectingClient:
    """RpcClient facade that re-dials a lost connection on the NEXT call —
    lets drivers, workers, and node agents ride out a conductor restart
    (reference: the GCS client's reconnect-with-backoff,
    src/ray/gcs/gcs_client/gcs_client.cc).

    A call already in flight when the connection drops still raises
    ConnectionLost — re-sending it here could double-execute a
    non-idempotent method (e.g. lease_worker); recovery is the caller's
    retry, made cheap because the re-dial happens underneath."""

    def __init__(self, address: Tuple[str, int], connect_timeout: float = 10.0,
                 connect_retries: int = 0, retry_interval: float = 0.3):
        self.address = tuple(address)
        self._connect_timeout = connect_timeout
        self._retry_interval = retry_interval
        self._lock = threading.Lock()
        self._client = RpcClient(address, connect_timeout=connect_timeout,
                                 connect_retries=connect_retries,
                                 retry_interval=retry_interval)
        self._shutdown = False

    @property
    def _closed(self) -> bool:
        """Closed for good (close() was called). A dropped connection is
        not 'closed' — the next call re-dials."""
        return self._shutdown

    def _live(self) -> RpcClient:
        with self._lock:
            if self._shutdown:
                raise ConnectionLost(f"client to {self.address} shut down")
            if not self._client._closed:
                return self._client
        # dial outside the lock; a brief outage gets a couple of retries
        nc = RpcClient(self.address, connect_timeout=self._connect_timeout,
                       connect_retries=2,
                       retry_interval=self._retry_interval)
        with self._lock:
            if self._shutdown or not self._client._closed:
                nc.close()
                if self._shutdown:
                    raise ConnectionLost(
                        f"client to {self.address} shut down")
                return self._client
            self._client = nc
            return nc

    def call(self, method: str, *args, timeout: Optional[float] = None,
             **kwargs) -> Any:
        return self._live().call(method, *args, timeout=timeout, **kwargs)

    def notify(self, method: str, *args, **kwargs) -> None:
        self._live().notify(method, *args, **kwargs)

    def start_call(self, method: str, *args, **kwargs):
        return self._live().start_call(method, *args, **kwargs)

    def finish_call(self, p, method: str = "",
                    timeout: Optional[float] = None) -> Any:
        return self._client.finish_call(p, method, timeout)

    def close(self) -> None:
        with self._lock:
            self._shutdown = True
            self._client.close()
